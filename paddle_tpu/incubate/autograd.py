"""paddle.incubate.autograd (ref: python/paddle/incubate/autograd/ (U) —
the functional-differentiation namespace: jvp/vjp and the Jacobian/
Hessian objects). TPU-native: thin objects over jax.jacrev/jax.hessian;
the jvp/vjp functionals are shared with paddle.autograd.

Lite scope, loud edges: the Jacobian/Hessian OBJECTS cover the common
single-tensor-xs, single-output case with full matrix slicing; multi-xs
block structure, multi-output funcs and is_batched raise
NotImplementedError pointing at `paddle.autograd.jacobian/hessian`
(which return the full block structures)."""

from __future__ import annotations

from ..autograd import hessian as _hessian_fn
from ..autograd import jacobian as _jacobian_fn
from ..autograd import jvp, vjp  # noqa: F401  (re-exports)

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "jacobian", "hessian"]

jacobian = _jacobian_fn
hessian = _hessian_fn


def _reject(kind, cond, what):
    if cond:
        raise NotImplementedError(
            f"{kind}: {what} is not supported by this lite object; use "
            "paddle.autograd.jacobian/hessian for the full block "
            "structure")


class Jacobian:
    """ref incubate.autograd.Jacobian: J = Jacobian(func, x); J[...]
    slices the (out_size, in_size)-structured jacobian with full
    numpy-style indexing (one xs tensor, one output tensor)."""

    def __init__(self, func, xs, is_batched=False):
        import jax

        from ..autograd import _fn_on_arrays, _unwrap

        _reject("Jacobian", is_batched, "is_batched=True")
        _reject("Jacobian", isinstance(xs, (list, tuple)),
                "multiple xs tensors")
        # reject multi-output BEFORE paying for the differentiation
        _, arrays = _unwrap(xs)
        f = _fn_on_arrays(func, True)
        _reject("Jacobian",
                isinstance(jax.eval_shape(f, *arrays), (tuple, list)),
                "a multi-output func")
        self._mat = _jacobian_fn(func, xs)

    @property
    def shape(self):
        return self._mat.shape

    def __getitem__(self, idx):
        return self._mat[idx]


class Hessian:
    """ref incubate.autograd.Hessian over a scalar-output func (one xs
    tensor); full numpy-style slicing of the (in, in) matrix."""

    def __init__(self, func, xs, is_batched=False):
        _reject("Hessian", is_batched, "is_batched=True")
        _reject("Hessian", isinstance(xs, (list, tuple)),
                "multiple xs tensors")
        self._mat = _hessian_fn(func, xs)

    @property
    def shape(self):
        return self._mat.shape

    def __getitem__(self, idx):
        return self._mat[idx]
