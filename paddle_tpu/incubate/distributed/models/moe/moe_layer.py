"""MoELayer: fixed-capacity einsum dispatch + expert-parallel all_to_all.

Reference parity: moe/moe_layer.py (U) — MoELayer dispatching tokens to
experts through `global_scatter`/`global_gather` NCCL all-to-alls
(SURVEY.md §2.1 N14, §2.2 P17).

TPU-native design: the GShard SPMD formulation. Dispatch/combine are
one-hot [T, E, C] einsums (static shapes, MXU-friendly, no index lists);
expert weights are STACKED on a leading expert dim (one big batched matmul
per expert layer — exactly what the MXU wants) instead of a Python list of
modules; expert parallelism is `lax.all_to_all` on the capacity buffers
over the chosen mesh axis, each rank computing its E/n local experts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .....core.op_call import apply
from .....core.tensor import Tensor
from .....distributed import collective_ctx
from .....distributed.shard_map_compat import axis_size as _axis_size
from .....nn import functional as F
from .....nn.initializer import XavierNormal
from .....nn.layer.layers import Layer
from .gate import GATES


class MoELayer(Layer):
    """Feed-forward MoE block: x -> gate -> expert MLPs -> combine.

    Args mirror the reference MoELayer where applicable; experts are an
    internal stacked MLP (d_model -> d_hidden -> d_model, `activation`).
    `axis_name` selects the expert-parallel mesh axis ('dp' is the usual EP
    group — the reference builds its moe_group over data ranks).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 top_k=None, capacity_factor=None, activation="gelu",
                 axis_name="dp", moe_group=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.axis_name = getattr(moe_group, "axis_name", None) or axis_name
        if isinstance(gate, str):
            kwargs = {}
            if capacity_factor is not None:
                kwargs["capacity_factor"] = capacity_factor
            if gate == "naive" and top_k is not None:
                kwargs["top_k"] = top_k
            gate = GATES[gate](**kwargs)
        self.gate = gate
        self.activation = activation
        self.l_aux = None  # set each forward (ref keeps it on the layer)
        self.tokens_per_expert = None  # [E] per-expert load, set each forward

        self.gate_weight = self.create_parameter(
            [d_model, num_experts], default_initializer=XavierNormal())
        self.w1 = self.create_parameter(
            [num_experts, d_model, d_hidden], default_initializer=XavierNormal())
        self.b1 = self.create_parameter([num_experts, 1, d_hidden], is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model], default_initializer=XavierNormal())
        self.b2 = self.create_parameter([num_experts, 1, d_model], is_bias=True)
        # expert weights shard over the EP axis
        for p in (self.w1, self.b1, self.w2, self.b2):
            p._sharding_axes = (self.axis_name,) + (None,) * (p._data.ndim - 1)

    # ------------------------------------------------------------ experts
    def _experts(self, x_ecm, w1, b1, w2, b2):
        """x [E_loc, C', M] with stacked weights -> [E_loc, C', M]."""
        act = getattr(jax.nn, self.activation)
        h = jnp.einsum("ecm,emh->ech", x_ecm, w1,
                       preferred_element_type=jnp.float32).astype(x_ecm.dtype)
        h = act(h + b1)
        y = jnp.einsum("ech,ehm->ecm", h, w2,
                       preferred_element_type=jnp.float32).astype(x_ecm.dtype)
        return y + b2

    def _forward_arrays(self, x, gw, w1, b1, w2, b2, axis):
        """x [T, M]; returns (y [T, M], aux loss scalar,
        tokens-per-expert [E])."""
        logits = jnp.einsum("tm,me->te", x, gw,
                            preferred_element_type=jnp.float32)
        dispatch, combine, aux = self.gate(logits)
        # [T, E, C] one-hot dispatch summed over tokens and capacity
        # slots = tokens routed to each expert (post-drop); the ledger's
        # expert-load skew signal
        tokens_per_expert = dispatch.astype(jnp.float32).sum(axis=(0, 2))
        expert_in = jnp.einsum("tec,tm->ecm", dispatch.astype(x.dtype), x)

        if axis is not None:
            n = _axis_size(axis)
            e_loc = self.num_experts // n
            # [E, C, M] -> send each rank its experts' buffers, gather the
            # buffers every rank built for OUR experts along capacity
            expert_in = expert_in.reshape(n, e_loc, -1, x.shape[-1])
            # split dim0 (destination rank) and restack it at dim0 as the
            # SOURCE rank: out[s] = rank s's buffers for OUR experts
            expert_in = lax.all_to_all(expert_in, axis, split_axis=0,
                                       concat_axis=0, tiled=False)
            # [n, e_loc, C, M] -> [e_loc, n*C, M]
            expert_in = expert_in.transpose(1, 0, 2, 3).reshape(
                e_loc, -1, x.shape[-1])
            i = lax.axis_index(axis)
            w1 = lax.dynamic_slice_in_dim(w1, i * e_loc, e_loc, 0)
            b1 = lax.dynamic_slice_in_dim(b1, i * e_loc, e_loc, 0)
            w2 = lax.dynamic_slice_in_dim(w2, i * e_loc, e_loc, 0)
            b2 = lax.dynamic_slice_in_dim(b2, i * e_loc, e_loc, 0)
            out = self._experts(expert_in, w1, b1, w2, b2)
            # reverse: [e_loc, n*C, M] -> [n, e_loc, C, M] -> [E, C, M]
            out = out.reshape(e_loc, n, -1, x.shape[-1]).transpose(1, 0, 2, 3)
            out = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
            # [n, e_loc, C, M], dim0 = expert-owner rank -> global expert order
            out = out.reshape(self.num_experts, -1, x.shape[-1])
        else:
            out = self._experts(expert_in, w1, b1, w2, b2)

        y = jnp.einsum("tec,ecm->tm", combine.astype(x.dtype), out)
        return y, aux, tokens_per_expert

    def forward(self, x):
        axis = collective_ctx.current_axis(self.axis_name)
        shape = x.shape
        m = shape[-1]

        def f(xa, gw, w1, b1, w2, b2):
            flat = xa.reshape(-1, m)
            y, aux, tok = self._forward_arrays(
                flat, gw, w1, b1, w2, b2, axis)
            return y.reshape(xa.shape), aux, tok

        y, aux, tok = apply(f, x, self.gate_weight, self.w1, self.b1,
                            self.w2, self.b2, _op_name="moe")
        self.l_aux = aux
        # like l_aux, recorded on the layer each forward; callers feed it
        # to observability.comms.observe_expert_load OUTSIDE the traced
        # region (under jit/shard_map it is a tracer here)
        self.tokens_per_expert = tok
        return y

    def extra_repr(self):
        return (f"d_model={self.d_model}, experts={self.num_experts}, "
                f"gate={type(self.gate).__name__}, axis={self.axis_name}")
