"""MoE / expert parallelism (ref: python/paddle/incubate/distributed/models/
moe/ (U) — MoELayer, GShard/Switch gates, global_scatter/global_gather
all-to-all dispatch; SURVEY.md §2.2 P17)."""

from .gate import GShardGate, NaiveGate, SwitchGate
from .moe_layer import MoELayer

__all__ = ["MoELayer", "GShardGate", "SwitchGate", "NaiveGate"]
