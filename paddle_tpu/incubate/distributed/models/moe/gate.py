"""MoE gates.

Reference parity: moe/gate/{gshard_gate,switch_gate,naive_gate}.py (U).
TPU-native: gates emit fixed-capacity one-hot dispatch/combine tensors
(the GShard einsum formulation) instead of index lists — static shapes are
what XLA/MXU need; token dropping happens via capacity masking, not
variable-length buffers.

All return (dispatch [T,E,C] bool-ish f32, combine [T,E,C] f32, aux_loss).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _capacity(num_tokens, num_experts, top_k, capacity_factor):
    cap = int(num_tokens * top_k * capacity_factor / num_experts)
    return max(cap, 1)


def _one_hot_dispatch(expert_idx, gate_w, num_experts, capacity):
    """expert_idx [T] int, gate_w [T] f32 -> dispatch/combine [T, E, C].

    Position within each expert's buffer = cumulative count of earlier tokens
    routed to the same expert; tokens past capacity are dropped.
    """
    t = expert_idx.shape[0]
    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)  # [T,E]
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot                     # [T,E]
    pos_in_e = jnp.sum(pos, axis=-1).astype(jnp.int32)                    # [T]
    keep = pos_in_e < capacity
    pos_oh = jax.nn.one_hot(pos_in_e, capacity, dtype=jnp.float32)        # [T,C]
    dispatch = onehot[:, :, None] * pos_oh[:, None, :]                    # [T,E,C]
    dispatch = dispatch * keep[:, None, None].astype(jnp.float32)
    combine = dispatch * gate_w[:, None, None]
    return dispatch, combine


def _load_balance_loss(probs, expert_idx, num_experts):
    """GShard/Switch aux loss: E * Σ_e f_e · P_e."""
    me = jnp.mean(probs, axis=0)                                  # [E]
    ce = jnp.mean(jax.nn.one_hot(expert_idx, num_experts, dtype=probs.dtype),
                  axis=0)
    return num_experts * jnp.sum(me * ce)


class NaiveGate:
    """ref NaiveGate: plain top-k, no aux loss."""

    top_k = 2

    def __init__(self, top_k=2, capacity_factor=1.0):
        self.top_k = top_k
        self.capacity_factor = capacity_factor

    def __call__(self, logits):
        t, e = logits.shape
        cap = _capacity(t, e, self.top_k, self.capacity_factor)
        probs = jax.nn.softmax(logits, axis=-1)
        disp = None
        comb = None
        remaining = probs
        occupancy = jnp.zeros((e,), jnp.float32)  # slots used by prior rounds
        for _ in range(self.top_k):
            idx = jnp.argmax(remaining, axis=-1)
            w = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
            oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)
            pos = (jnp.cumsum(oh, axis=0) - 1.0) * oh + occupancy * oh
            pos_in_e = jnp.sum(pos, axis=-1).astype(jnp.int32)
            keep = pos_in_e < cap
            pos_oh = jax.nn.one_hot(pos_in_e, cap, dtype=jnp.float32)
            d = oh[:, :, None] * pos_oh[:, None, :] \
                * keep[:, None, None].astype(jnp.float32)
            c = d * w[:, None, None]
            disp = d if disp is None else jnp.maximum(disp, d)
            comb = c if comb is None else comb + c
            occupancy = occupancy + jnp.sum(oh, axis=0)
            remaining = remaining * (1.0 - oh.astype(probs.dtype))
        return disp, comb, jnp.zeros((), probs.dtype)


class SwitchGate:
    """ref SwitchGate: top-1 routing with load-balance aux loss."""

    top_k = 1

    def __init__(self, capacity_factor=1.25, aux_loss_weight=1.0):
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight

    def __call__(self, logits):
        t, e = logits.shape
        cap = _capacity(t, e, 1, self.capacity_factor)
        probs = jax.nn.softmax(logits, axis=-1)
        idx = jnp.argmax(probs, axis=-1)
        w = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        dispatch, combine = _one_hot_dispatch(idx, w, e, cap)
        aux = _load_balance_loss(probs, idx, e) * self.aux_loss_weight
        return dispatch, combine, aux


class GShardGate:
    """ref GShardGate: top-2 with normalized weights + aux loss."""

    top_k = 2

    def __init__(self, capacity_factor=2.0, aux_loss_weight=1.0):
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight

    def __call__(self, logits):
        t, e = logits.shape
        cap = _capacity(t, e, 2, self.capacity_factor)
        probs = jax.nn.softmax(logits, axis=-1)

        idx1 = jnp.argmax(probs, axis=-1)
        mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)
        probs2 = probs * (1.0 - mask1)
        idx2 = jnp.argmax(probs2, axis=-1)

        w1 = jnp.take_along_axis(probs, idx1[:, None], -1)[:, 0]
        w2 = jnp.take_along_axis(probs, idx2[:, None], -1)[:, 0]
        denom = jnp.maximum(w1 + w2, 1e-9)
        w1, w2 = w1 / denom, w2 / denom

        # top-1 tokens first in each expert buffer (they matter more), then
        # top-2 tokens fill remaining capacity
        d1, c1 = _one_hot_dispatch(idx1, w1, e, cap)
        # offset top-2 positions past the top-1 occupancy of that expert
        oh1 = jax.nn.one_hot(idx1, e, dtype=jnp.float32)
        oh2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32)
        count1 = jnp.sum(oh1, axis=0)                             # [E]
        pos2 = (jnp.cumsum(oh2, axis=0) - 1.0) * oh2 + count1 * oh2
        pos_in_e2 = jnp.sum(pos2, axis=-1).astype(jnp.int32)
        keep2 = pos_in_e2 < cap
        pos_oh2 = jax.nn.one_hot(pos_in_e2, cap, dtype=jnp.float32)
        d2 = oh2[:, :, None] * pos_oh2[:, None, :] \
            * keep2[:, None, None].astype(jnp.float32)
        c2 = d2 * w2[:, None, None]

        dispatch = jnp.maximum(d1, d2)
        combine = c1 + c2
        aux = _load_balance_loss(probs, idx1, e) * self.aux_loss_weight
        return dispatch, combine, aux


GATES = {"naive": NaiveGate, "switch": SwitchGate, "gshard": GShardGate}
