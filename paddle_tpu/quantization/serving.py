"""Weight-only PTQ for the serving engine (int8 decode weights).

Decode is weight-bandwidth-bound (DECODE_BENCH.json: fused decode caps
near 47% of the weight roofline), so the cheapest 2x on the bound is
storing matmul weights as int8 and paying a per-channel multiply to
rebuild them inside the program: XLA fuses ``q.astype(f32) * scale``
into the matmul's weight read, so the bytes streamed from HBM per step
halve while the arithmetic stays f32.

Scale layout: one absmax scale per OUTPUT channel.  ``Linear`` stores
its weight ``[in_features, out_features]`` and contracts over axis 0,
so the per-output-channel scale is an absmax over axis 0 with shape
``[1, out_features]`` — it broadcasts over the contraction axis, which
keeps each output column's quantization error independent of every
other column (a single per-tensor scale would let one outlier column
crush the resolution of all of them).

The floor is applied PER CHANNEL (``maximum(absmax, 1e-8)`` on the
[1, out] array, before any division): an all-zero output channel —
common in pruned or freshly-initialized heads — quantizes to exact
zeros instead of propagating ``0/0`` NaNs through the whole column.

``quantize_for_serving`` walks a CausalLM Layer tree and quantizes
every ``Linear`` weight it can map back to a ``state_dict`` name
(q/k/v/o projections, the SwiGLU MLP, the LM head).  Embeddings,
norms, and biases stay in their original dtype: they are a rounding
hazard (embedding rows feed every downstream computation) and a
rounding waste (norm gains and biases are vectors, not byte traffic).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


#: per-channel absmax floor: an all-zero channel quantizes to zeros
#: (scale floor / qmax) instead of dividing by zero
SCALE_FLOOR = 1e-8


def channelwise_scales(w, channel_axis=-1, quant_bits=8):
    """Per-channel symmetric quantization step for ``w``: absmax over
    every axis except ``channel_axis``, floored at :data:`SCALE_FLOOR`
    per channel, divided by the int range.  Returned with ``keepdims``
    so it broadcasts against ``w`` directly."""
    qmax = float(2 ** (quant_bits - 1) - 1)
    axis = channel_axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes,
                     keepdims=True)
    return jnp.maximum(absmax, SCALE_FLOOR) / qmax


def quantize_weight(w, channel_axis=-1, quant_bits=8):
    """Symmetric per-channel int8 quantization: returns ``(q, scale)``
    with ``q`` int8 shaped like ``w`` and ``scale`` f32 broadcastable
    against it (``dequantize_weight`` inverts)."""
    qmax = float(2 ** (quant_bits - 1) - 1)
    scale = channelwise_scales(w, channel_axis, quant_bits)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax, qmax)
    return q.astype(jnp.int8), scale


def dequantize_weight(q, scale, dtype=jnp.float32):
    """Rebuild the fp weight inside a traced program.  Under jit the
    multiply fuses into the consuming matmul's weight read, so only the
    int8 bytes (plus the tiny scale vector) cross HBM."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


@dataclass
class QuantizedWeight:
    """One serving-quantized parameter: int8 payload + f32 per-channel
    scale + the dtype ``dequantize()`` must restore."""

    q: jax.Array
    scale: jax.Array
    dtype: object

    @property
    def pair(self):
        """The (q, scale) pytree the engine threads through its jitted
        programs in place of the fp array."""
        return (self.q, self.scale)

    def dequantize(self):
        return dequantize_weight(self.q, self.scale, self.dtype)

    @property
    def nbytes(self):
        return int(self.q.size * self.q.dtype.itemsize
                   + self.scale.size * self.scale.dtype.itemsize)


def quantize_for_serving(model, quant_bits=8):
    """Absmax-calibrate every ``Linear`` weight of ``model`` for
    weight-only serving: returns ``{state_dict name: QuantizedWeight}``
    for the projections worth quantizing (matmul weights), leaving
    embeddings/norms/biases untouched.

    Pure PTQ — no calibration data needed: weight quantization only
    depends on the weights themselves (activations stay fp, so there is
    no activation-range estimation problem).  The caller substitutes
    ``QuantizedWeight.pair`` for the fp array and dequantizes inline
    (the serving engine does this in ``_run_model``)."""
    from ..nn.layer.common import Linear

    by_id = {}
    for name, t in model.state_dict().items():
        by_id[id(t)] = name
    out = {}

    def walk(layer):
        for _, child in layer.named_children():
            if isinstance(child, Linear):
                name = by_id.get(id(child.weight))
                if name is not None:
                    w = child.weight._data
                    q, scale = quantize_weight(w, channel_axis=-1,
                                               quant_bits=quant_bits)
                    out[name] = QuantizedWeight(q, scale, w.dtype)
            walk(child)

    walk(model)
    return out
