"""paddle.quantization parity (ref: python/paddle/quantization/ (U):
QuantConfig, QAT, PTQ with observer/fake-quant factories).

TPU-native: fake-quant is a straight-through-estimator round expressed with
`jax.custom_vjp` (clip-gradient STE), so QAT training steps stay one fused
XLA program. int8 simulation only — actual int8 MXU kernels are an XLA
lowering concern, not a framework one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..tensor.creation import _as_t


@jax.custom_vjp
def _fake_quant_ste(x, scale, qmin, qmax):
    q = jnp.clip(jnp.round(x / scale), qmin, qmax)
    return q * scale


def _fq_fwd(x, scale, qmin, qmax):
    return _fake_quant_ste(x, scale, qmin, qmax), (x, scale, qmin, qmax)


def _fq_bwd(res, g):
    x, scale, qmin, qmax = res
    # STE with clipping: gradient passes through inside the representable
    # range, zero outside
    inside = (x / scale >= qmin) & (x / scale <= qmax)
    return (jnp.where(inside, g, 0.0), None, None, None)


_fake_quant_ste.defvjp(_fq_fwd, _fq_bwd)


class BaseObserver:
    """Tracks the quantization scale for one tensor."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self.qmax = float(2 ** (quant_bits - 1) - 1)
        self.qmin = -self.qmax

    def scale(self, x):
        raise NotImplementedError

    def fake_quant(self, x):
        s = jnp.maximum(self.scale(x), 1e-8) / self.qmax
        return _fake_quant_ste(x, s, self.qmin, self.qmax)


class AbsmaxObserver(BaseObserver):
    """Per-tensor abs-max (ref AbsmaxObserver)."""

    def scale(self, x):
        return jnp.max(jnp.abs(x))


class PerChannelAbsmaxObserver(BaseObserver):
    """Per-channel abs-max over every axis except ``channel_axis`` (ref
    AbsmaxObserver with quant_axis): the scale is an ARRAY broadcastable
    against ``x``, so ``fake_quant``'s ``maximum(scale, 1e-8)`` floor
    applies per channel — an all-zero channel quantizes to exact zeros
    instead of dividing by zero, and one outlier channel cannot crush
    every other channel's resolution the way a post-max per-tensor
    scale would.  This is the observer behind the serving engine's
    weight-only int8 path (see ``quantization.serving``)."""

    def __init__(self, quant_bits=8, channel_axis=-1):
        super().__init__(quant_bits)
        self.channel_axis = channel_axis

    def scale(self, x):
        axis = self.channel_axis % x.ndim
        reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
        return jnp.max(jnp.abs(x), axis=reduce_axes, keepdims=True)


class EMAObserver(BaseObserver):
    """Moving-average abs-max (ref EMAObserver); state updates eagerly
    between steps (host-side float), the in-graph scale is the snapshot."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._ema = None

    def observe(self, x_value):
        m = float(jnp.max(jnp.abs(x_value)))
        self._ema = (m if self._ema is None
                     else self.moving_rate * self._ema
                     + (1 - self.moving_rate) * m)

    def scale(self, x):
        if self._ema is not None:
            return jnp.asarray(self._ema, jnp.float32)
        return jnp.max(jnp.abs(x))


class FakeQuanterWithAbsMax(AbsmaxObserver):
    pass


class QuantConfig:
    """ref QuantConfig: maps layers (by type or instance) to quanters."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation or AbsmaxObserver()
        self.weight = weight or AbsmaxObserver()
        self._type_configs = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type_configs[t] = (activation or self.activation,
                                     weight or self.weight)

    def config_for(self, layer):
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


class QuantedLayer(Layer):
    """Wraps Linear/Conv2D: fake-quants input activation and weight."""

    def __init__(self, layer, act_observer, weight_observer):
        super().__init__()
        self.inner = layer
        self._act_obs = act_observer
        self._w_obs = weight_observer

    def forward(self, x):
        xt = _as_t(x)
        if hasattr(self._act_obs, "observe") and not isinstance(
                xt._data, jax.core.Tracer):
            # eager calibration pass (PTQ): stateful observers see the batch
            self._act_obs.observe(xt._data)
        xq = apply(lambda a: self._act_obs.fake_quant(a), xt,
                   _op_name="fake_quant_act")
        w = self.inner.weight
        wq = apply(lambda a: self._w_obs.fake_quant(a), w,
                   _op_name="fake_quant_weight")
        # shadow the parameter with the fake-quanted tensor for this call
        # (instance __dict__ wins over the _parameters registry lookup)
        object.__setattr__(self.inner, "weight", wq)
        try:
            return self.inner(xq)
        finally:
            object.__delattr__(self.inner, "weight")


_QUANTABLE = (Linear, Conv2D)


def _swap_layers(model, config, cls):
    for name, child in list(model.named_children()):
        if isinstance(child, _QUANTABLE):
            act, w = config.config_for(child)
            import copy

            setattr(model, name, cls(child, copy.deepcopy(act),
                                     copy.deepcopy(w)))
        else:
            _swap_layers(child, config, cls)
    return model


class QAT:
    """Quantization-aware training (ref paddle.quantization.QAT)."""

    def __init__(self, config=None):
        self.config = config or QuantConfig()

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self.config, QuantedLayer)

    def convert(self, model, inplace=False):
        """Strip fake-quant wrappers, baking quantized weights in."""
        if not inplace:
            import copy

            model = copy.deepcopy(model)

        def strip(m):
            for name, child in list(m.named_children()):
                if isinstance(child, QuantedLayer):
                    inner = child.inner
                    inner.weight.set_value(
                        Tensor(child._w_obs.fake_quant(inner.weight._data)))
                    setattr(m, name, inner)
                else:
                    strip(child)
            return m

        return strip(model)


class PTQ(QAT):
    """Post-training quantization: same wrappers, calibration-driven scales
    (run representative batches through the quantized model, stateful
    observers record the activations). Defaults activations to EMAObserver —
    a stateless observer would silently degrade to per-batch dynamic
    quantization."""

    def __init__(self, config=None):
        if config is None:
            config = QuantConfig(activation=EMAObserver())
        elif not hasattr(config.activation, "observe"):
            raise ValueError(
                "PTQ needs a stateful activation observer (e.g. EMAObserver);"
                f" got {type(config.activation).__name__}")
        super().__init__(config)


from .serving import (QuantizedWeight, channelwise_scales,  # noqa: E402
                      dequantize_weight, quantize_for_serving,
                      quantize_weight)

__all__ = [
    "QuantConfig", "QAT", "PTQ", "AbsmaxObserver", "EMAObserver",
    "FakeQuanterWithAbsMax", "QuantedLayer", "BaseObserver",
    "PerChannelAbsmaxObserver", "QuantizedWeight", "channelwise_scales",
    "quantize_weight", "dequantize_weight", "quantize_for_serving",
]
