"""ctypes bindings for the native runtime core (see native.cc).

Build-on-first-import with g++ (no pybind11 in this image — SURVEY.md §2.1
N24 maps to plain C ABI + ctypes). The .so is cached next to the source and
rebuilt when native.cc changes. Every entry point degrades to a pure-Python
fallback if the toolchain is unavailable, so the framework never hard-fails.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "native.cc")

_lib = None
_lib_err = None
_lock = threading.Lock()


def _build_and_load():
    global _lib, _lib_err
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_DIR, f"_native_{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + ".tmp"
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SRC, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            _lib_err = getattr(e, "stderr", str(e))
            return None
        os.replace(tmp, so_path)
        # drop stale builds
        for f_ in os.listdir(_DIR):
            if f_.startswith("_native_") and f_.endswith(".so") \
                    and f_ != os.path.basename(so_path):
                try:
                    os.unlink(os.path.join(_DIR, f_))
                except OSError:
                    pass
    lib = ctypes.CDLL(so_path)
    lib.pt_trace_begin.argtypes = [ctypes.c_char_p]
    lib.pt_trace_instant.argtypes = [ctypes.c_char_p]
    lib.pt_trace_export.argtypes = [ctypes.c_char_p]
    lib.pt_trace_export.restype = ctypes.c_int
    lib.pt_trace_event_count.restype = ctypes.c_uint64
    lib.pt_buf_alloc.argtypes = [ctypes.c_size_t]
    lib.pt_buf_alloc.restype = ctypes.c_void_p
    lib.pt_buf_free.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.pt_buf_stats.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
    lib.pt_collate.argtypes = [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_void_p),
                               ctypes.c_size_t, ctypes.c_size_t, ctypes.c_int]
    lib.pt_pwrite_chunks.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                     ctypes.c_void_p, ctypes.c_uint64,
                                     ctypes.c_int]
    lib.pt_pwrite_chunks.restype = ctypes.c_int
    lib.pt_pread_chunks.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_void_p, ctypes.c_uint64,
                                    ctypes.c_int]
    lib.pt_pread_chunks.restype = ctypes.c_int
    lib.prec_open.argtypes = [ctypes.c_char_p]
    lib.prec_open.restype = ctypes.c_int64
    lib.prec_count.argtypes = [ctypes.c_int64]
    lib.prec_count.restype = ctypes.c_int64
    lib.prec_size.argtypes = [ctypes.c_int64, ctypes.c_int64]
    lib.prec_size.restype = ctypes.c_int64
    lib.prec_read.argtypes = [ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
    lib.prec_read.restype = ctypes.c_int
    lib.prec_read_many.argtypes = [ctypes.c_int64,
                                   ctypes.POINTER(ctypes.c_int64),
                                   ctypes.c_int, ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint64),
                                   ctypes.c_int]
    lib.prec_read_many.restype = ctypes.c_int
    lib.prec_close.argtypes = [ctypes.c_int64]
    return lib


def get_lib():
    """The loaded native library, building it on first use (None if no
    toolchain)."""
    global _lib
    if _lib is None:
        with _lock:
            if _lib is None:
                _lib = _build_and_load()
    return _lib


def available() -> bool:
    return get_lib() is not None


# ------------------------------------------------------------- tracer API

def trace_enable(on=True):
    lib = get_lib()
    if lib:
        lib.pt_trace_enable(1 if on else 0)


def trace_begin(name: str):
    lib = get_lib()
    if lib:
        lib.pt_trace_begin(name.encode())


def trace_end():
    lib = get_lib()
    if lib:
        lib.pt_trace_end()


def trace_export(path: str) -> bool:
    lib = get_lib()
    return bool(lib) and lib.pt_trace_export(path.encode()) == 0


def trace_clear():
    lib = get_lib()
    if lib:
        lib.pt_trace_clear()


def trace_event_count() -> int:
    lib = get_lib()
    return int(lib.pt_trace_event_count()) if lib else 0


# ------------------------------------------------------------ buffer pool

def buf_stats():
    lib = get_lib()
    if not lib:
        return {"bytes_live": 0, "bytes_pooled": 0, "n_alloc": 0, "n_reuse": 0}
    out = (ctypes.c_uint64 * 4)()
    lib.pt_buf_stats(out)
    return {"bytes_live": out[0], "bytes_pooled": out[1],
            "n_alloc": out[2], "n_reuse": out[3]}


class StagingBuffer:
    """Pooled page-aligned host buffer (ref pinned allocator N18) exposed as
    a numpy array for H2D staging."""

    def __init__(self, nbytes):
        import numpy as np

        self.nbytes = int(nbytes)
        lib = get_lib()
        if lib:
            self._ptr = lib.pt_buf_alloc(self.nbytes)
            buf = (ctypes.c_char * self.nbytes).from_address(self._ptr)
            self.array = np.frombuffer(buf, dtype=np.uint8)
        else:
            self._ptr = None
            self.array = np.empty(self.nbytes, dtype=np.uint8)

    def release(self):
        if self._ptr is not None:
            get_lib().pt_buf_free(self._ptr, self.nbytes)
            self._ptr = None
            self.array = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


# ------------------------------------------------------------ parallel IO

def pwrite(path: str, offset: int, view) -> bool:
    """Parallel positional write of a contiguous buffer (C-order bytes view).
    Returns False (caller falls back to Python IO) if no native lib."""
    lib = get_lib()
    if lib is None:
        return False
    import numpy as np

    arr = np.ascontiguousarray(view).view(np.uint8).reshape(-1)
    rc = lib.pt_pwrite_chunks(path.encode(), offset,
                              arr.ctypes.data_as(ctypes.c_void_p),
                              arr.nbytes, 0)
    if rc != 0:
        raise OSError(rc, f"pt_pwrite_chunks({path!r}) failed")
    return True


def pread(path: str, offset: int, out) -> bool:
    """Parallel positional read into a preallocated contiguous ndarray."""
    lib = get_lib()
    if lib is None:
        return False
    rc = lib.pt_pread_chunks(path.encode(), offset,
                             out.ctypes.data_as(ctypes.c_void_p),
                             out.nbytes, 0)
    if rc != 0:
        raise OSError(rc, f"pt_pread_chunks({path!r}) failed")
    return True


# --------------------------------------------------------------- collate

def collate_stack(samples, out=None):
    """np.stack(samples) through the native parallel-memcpy path. Samples
    must be same-shape, same-dtype, C-contiguous ndarrays."""
    import numpy as np

    lib = get_lib()
    first = samples[0]
    if (lib is None or not first.flags["C_CONTIGUOUS"]
            or any(s.shape != first.shape or s.dtype != first.dtype
                   or not s.flags["C_CONTIGUOUS"] for s in samples[1:])):
        return np.stack(samples)
    n = len(samples)
    if out is None:
        out = np.empty((n,) + first.shape, dtype=first.dtype)
    ptrs = (ctypes.c_void_p * n)(
        *[s.ctypes.data_as(ctypes.c_void_p) for s in samples])
    lib.pt_collate(out.ctypes.data_as(ctypes.c_void_p), ptrs, n,
                   first.nbytes, 0)
    return out
