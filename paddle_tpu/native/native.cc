// paddle_tpu native runtime core.
//
// Reference parity: the C++ side of the reference framework that is NOT
// subsumed by XLA/PJRT (SURVEY.md §2.1):
//   * host tracer (N20, paddle/fluid/platform/profiler/host_tracer.cc):
//     RecordEvent span collection + chrome-trace export, here a lock-free
//     per-thread buffer design so instrumentation stays ~ns-cheap.
//   * host staging allocator (N18, paddle/fluid/memory/allocation/
//     pinned_allocator.cc): page-aligned pooled host buffers for H2D staging
//     with reuse stats (the device side is XLA's BFC — nothing to build).
//   * DataLoader batch collation (P6 worker core): parallel memcpy gather of
//     sample buffers into one batch buffer, off the GIL.
//
// Built by paddle_tpu/native/__init__.py with g++ -O2 -shared; bound via
// ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cerrno>
#include <functional>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// ----------------------------------------------------------- host tracer

namespace {

struct TraceEvent {
  uint32_t name_id;
  uint64_t ts_us;
  uint64_t dur_us;
};

struct OpenSpan {
  uint32_t name_id;
  uint64_t ts_us;
};

uint64_t now_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct ThreadTrace {
  uint64_t tid;
  std::vector<TraceEvent> events;
  std::vector<OpenSpan> stack;
};

std::mutex g_trace_mu;                      // registry + name table only
std::vector<ThreadTrace*> g_threads;        // owned forever (leak by design)
std::unordered_map<std::string, uint32_t> g_name_ids;
std::vector<std::string> g_names;
std::atomic<bool> g_trace_on{false};

ThreadTrace* tls_trace() {
  thread_local ThreadTrace* t = nullptr;
  if (t == nullptr) {
    t = new ThreadTrace();
    t->tid = std::hash<std::thread::id>()(std::this_thread::get_id()) & 0xffffff;
    std::lock_guard<std::mutex> lk(g_trace_mu);
    g_threads.push_back(t);
  }
  return t;
}

uint32_t intern_name(const char* name) {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  auto it = g_name_ids.find(name);
  if (it != g_name_ids.end()) return it->second;
  uint32_t id = static_cast<uint32_t>(g_names.size());
  g_names.emplace_back(name);
  g_name_ids.emplace(name, id);
  return id;
}

}  // namespace

extern "C" {

void pt_trace_enable(int on) { g_trace_on.store(on != 0); }

int pt_trace_enabled() { return g_trace_on.load() ? 1 : 0; }

void pt_trace_begin(const char* name) {
  if (!g_trace_on.load(std::memory_order_relaxed)) return;
  ThreadTrace* t = tls_trace();
  t->stack.push_back({intern_name(name), now_us()});
}

void pt_trace_end() {
  if (!g_trace_on.load(std::memory_order_relaxed)) return;
  ThreadTrace* t = tls_trace();
  if (t->stack.empty()) return;
  OpenSpan s = t->stack.back();
  t->stack.pop_back();
  t->events.push_back({s.name_id, s.ts_us, now_us() - s.ts_us});
}

void pt_trace_instant(const char* name) {
  if (!g_trace_on.load(std::memory_order_relaxed)) return;
  ThreadTrace* t = tls_trace();
  t->events.push_back({intern_name(name), now_us(), 0});
}

uint64_t pt_trace_event_count() {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  uint64_t n = 0;
  for (auto* t : g_threads) n += t->events.size();
  return n;
}

// chrome-trace JSON (ref chrometracing_logger.cc). Returns 0 on success.
int pt_trace_export(const char* path) {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[", f);
  bool first = true;
  for (auto* t : g_threads) {
    for (const TraceEvent& e : t->events) {
      if (!first) std::fputc(',', f);
      first = false;
      std::fprintf(f,
                   "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%llu,"
                   "\"ts\":%llu,\"dur\":%llu}",
                   g_names[e.name_id].c_str(),
                   static_cast<unsigned long long>(t->tid),
                   static_cast<unsigned long long>(e.ts_us),
                   static_cast<unsigned long long>(e.dur_us));
    }
  }
  std::fputs("]}", f);
  std::fclose(f);
  return 0;
}

void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_trace_mu);
  for (auto* t : g_threads) t->events.clear();
}

}  // extern "C"

// ----------------------------------------------- host staging buffer pool

namespace {

constexpr size_t kAlign = 4096;  // page-aligned: DMA-friendly staging

struct BufPool {
  std::mutex mu;
  // size-class (rounded to 64KiB) -> free buffers
  std::unordered_map<size_t, std::vector<void*>> free_list;
  std::atomic<uint64_t> bytes_live{0};
  std::atomic<uint64_t> bytes_pooled{0};
  std::atomic<uint64_t> n_alloc{0};
  std::atomic<uint64_t> n_reuse{0};
};

BufPool g_pool;

size_t size_class(size_t n) {
  constexpr size_t kGran = 64 * 1024;
  return (n + kGran - 1) / kGran * kGran;
}

}  // namespace

extern "C" {

void* pt_buf_alloc(size_t size) {
  size_t cls = size_class(size);
  {
    std::lock_guard<std::mutex> lk(g_pool.mu);
    auto it = g_pool.free_list.find(cls);
    if (it != g_pool.free_list.end() && !it->second.empty()) {
      void* p = it->second.back();
      it->second.pop_back();
      g_pool.bytes_pooled -= cls;
      g_pool.bytes_live += cls;
      g_pool.n_reuse++;
      return p;
    }
  }
  void* p = nullptr;
  if (posix_memalign(&p, kAlign, cls) != 0) return nullptr;
  g_pool.bytes_live += cls;
  g_pool.n_alloc++;
  return p;
}

void pt_buf_free(void* p, size_t size) {
  if (!p) return;
  size_t cls = size_class(size);
  std::lock_guard<std::mutex> lk(g_pool.mu);
  g_pool.free_list[cls].push_back(p);
  g_pool.bytes_live -= cls;
  g_pool.bytes_pooled += cls;
}

void pt_buf_trim() {
  std::lock_guard<std::mutex> lk(g_pool.mu);
  for (auto& kv : g_pool.free_list) {
    for (void* p : kv.second) std::free(p);
    g_pool.bytes_pooled -= kv.second.size() * kv.first;
    kv.second.clear();
  }
}

// out[0]=bytes_live out[1]=bytes_pooled out[2]=n_alloc out[3]=n_reuse
void pt_buf_stats(uint64_t* out) {
  out[0] = g_pool.bytes_live.load();
  out[1] = g_pool.bytes_pooled.load();
  out[2] = g_pool.n_alloc.load();
  out[3] = g_pool.n_reuse.load();
}

}  // extern "C"

// -------------------------------------------------- parallel batch collate

namespace {

class WorkerPool {
 public:
  explicit WorkerPool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i)
      workers_.emplace_back([this] { this->run(); });
  }

  void parallel_for(size_t n, const std::function<void(size_t)>& fn) {
    if (n == 0) return;
    std::unique_lock<std::mutex> lk(mu_);
    fn_ = &fn;
    next_.store(0);
    done_.store(0);
    total_ = n;
    epoch_++;
    cv_.notify_all();
    done_cv_.wait(lk, [this] { return done_.load() == total_; });
    fn_ = nullptr;
  }

 private:
  void run() {
    uint64_t seen_epoch = 0;
    for (;;) {
      const std::function<void(size_t)>* fn;
      size_t total;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [&] { return stop_ || epoch_ != seen_epoch; });
        if (stop_) return;
        seen_epoch = epoch_;
        fn = fn_;
        total = total_;
      }
      for (;;) {
        size_t i = next_.fetch_add(1);
        if (i >= total) break;
        (*fn)(i);
        if (done_.fetch_add(1) + 1 == total) {
          std::lock_guard<std::mutex> lk(mu_);
          done_cv_.notify_all();
        }
      }
    }
  }

  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(size_t)>* fn_ = nullptr;
  std::atomic<size_t> next_{0}, done_{0};
  size_t total_ = 0;
  uint64_t epoch_ = 0;
  bool stop_;
};

WorkerPool* pool(int nthreads) {
  static WorkerPool* p = new WorkerPool(
      std::max(2, std::min(nthreads > 0 ? nthreads : 8,
                           (int)std::thread::hardware_concurrency())));
  return p;
}

}  // namespace

extern "C" {

// Gather n sample buffers (srcs[i], bytes_per each) into dst, in parallel.
void pt_collate(void* dst, void** srcs, size_t n, size_t bytes_per,
                int nthreads) {
  char* out = static_cast<char*>(dst);
  if (n * bytes_per < (8u << 20)) {  // small batch: threads cost more
    for (size_t i = 0; i < n; ++i)
      std::memcpy(out + i * bytes_per, srcs[i], bytes_per);
    return;
  }
  // one contiguous range per task (per-item dispatch drowns in coordination)
  size_t n_tasks = 8;
  size_t per = (n + n_tasks - 1) / n_tasks;
  pool(nthreads)->parallel_for(n_tasks, [&](size_t t) {
    size_t lo = t * per, hi = std::min(n, lo + per);
    for (size_t i = lo; i < hi; ++i)
      std::memcpy(out + i * bytes_per, srcs[i], bytes_per);
  });
}

}  // extern "C"

// -------------------------------------------------- parallel checkpoint IO

#include <fcntl.h>
#include <unistd.h>

extern "C" {

// Parallel positional write of one contiguous buffer into path at
// file_offset. Returns 0 on success, errno otherwise. The file must exist
// (caller creates/truncates it and writes any header first).
int pt_pwrite_chunks(const char* path, uint64_t file_offset, const void* buf,
                     uint64_t nbytes, int nthreads) {
  int fd = ::open(path, O_WRONLY);
  if (fd < 0) return errno;
  const char* src = static_cast<const char*>(buf);
  std::atomic<int> err{0};
  const uint64_t kChunk = 16ull << 20;
  uint64_t n_tasks = (nbytes + kChunk - 1) / kChunk;
  if (n_tasks <= 1) {
    uint64_t off = 0;
    while (off < nbytes) {
      ssize_t w = ::pwrite(fd, src + off, nbytes - off, file_offset + off);
      if (w < 0) { err.store(errno); break; }
      off += (uint64_t)w;
    }
  } else {
    pool(nthreads)->parallel_for(n_tasks, [&](size_t t) {
      uint64_t lo = t * kChunk;
      uint64_t hi = std::min(nbytes, lo + kChunk);
      uint64_t off = lo;
      while (off < hi) {
        ssize_t w = ::pwrite(fd, src + off, hi - off, file_offset + off);
        if (w < 0) { err.store(errno); return; }
        off += (uint64_t)w;
      }
    });
  }
  ::close(fd);
  return err.load();
}

// Parallel positional read into one contiguous buffer. Returns 0 or errno.
int pt_pread_chunks(const char* path, uint64_t file_offset, void* buf,
                    uint64_t nbytes, int nthreads) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return errno;
  char* dst = static_cast<char*>(buf);
  std::atomic<int> err{0};
  const uint64_t kChunk = 16ull << 20;
  uint64_t n_tasks = (nbytes + kChunk - 1) / kChunk;
  if (n_tasks <= 1) {
    uint64_t off = 0;
    while (off < nbytes) {
      ssize_t r = ::pread(fd, dst + off, nbytes - off, file_offset + off);
      if (r < 0) { err.store(errno); break; }
      if (r == 0) { err.store(EIO); break; }
      off += (uint64_t)r;
    }
  } else {
    pool(nthreads)->parallel_for(n_tasks, [&](size_t t) {
      uint64_t lo = t * kChunk;
      uint64_t hi = std::min(nbytes, lo + kChunk);
      uint64_t off = lo;
      while (off < hi) {
        ssize_t r = ::pread(fd, dst + off, hi - off, file_offset + off);
        if (r < 0) { err.store(errno); return; }
        if (r == 0) { err.store(EIO); return; }
        off += (uint64_t)r;
      }
    });
  }
  ::close(fd);
  return err.load();
}

}  // extern "C"

// -------------------------------------------------- record file reader
// GIL-free sample store for the data pipeline (the reference's multiprocess
// DataLoader + pin_memory path, SURVEY.md §2.2 P6, done the host-native way:
// indexed binary records, parallel positional reads into pooled staging
// buffers, zero Python between syscall and numpy view).
//
// Format PTRECD01: 8-byte magic, then per record u64 little-endian payload
// length + payload. The offset index is built once at open by scanning.

namespace {

struct RecordFile {
  int fd = -1;
  std::vector<uint64_t> offsets;  // payload start per record
  std::vector<uint64_t> sizes;
};

std::mutex g_rec_mu;
std::unordered_map<int64_t, RecordFile*> g_rec;
int64_t g_rec_next = 1;

}  // namespace

extern "C" {

// Open + index. Returns handle > 0, or -errno / -EINVAL on bad magic.
int64_t prec_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -(int64_t)errno;
  char magic[8];
  if (::pread(fd, magic, 8, 0) != 8 || memcmp(magic, "PTRECD01", 8) != 0) {
    ::close(fd);
    return -(int64_t)EINVAL;
  }
  off_t end = ::lseek(fd, 0, SEEK_END);
  auto* rf = new RecordFile();
  rf->fd = fd;
  uint64_t off = 8;
  while ((off_t)off + 8 <= end) {
    uint64_t len;
    if (::pread(fd, &len, 8, off) != 8) break;
    off += 8;
    if (len > (uint64_t)end - off) break;  // truncated/corrupt tail: drop
    rf->offsets.push_back(off);
    rf->sizes.push_back(len);
    off += len;
  }
  std::lock_guard<std::mutex> lk(g_rec_mu);
  int64_t h = g_rec_next++;
  g_rec[h] = rf;
  return h;
}

int64_t prec_count(int64_t h) {
  std::lock_guard<std::mutex> lk(g_rec_mu);
  auto it = g_rec.find(h);
  return it == g_rec.end() ? -1 : (int64_t)it->second->offsets.size();
}

int64_t prec_size(int64_t h, int64_t i) {
  std::lock_guard<std::mutex> lk(g_rec_mu);
  auto it = g_rec.find(h);
  if (it == g_rec.end() || i < 0 || (size_t)i >= it->second->sizes.size())
    return -1;
  return (int64_t)it->second->sizes[i];
}

// Read record i into dst (must hold prec_size bytes). 0 on success.
int prec_read(int64_t h, int64_t i, void* dst) {
  RecordFile* rf;
  {
    std::lock_guard<std::mutex> lk(g_rec_mu);
    auto it = g_rec.find(h);
    if (it == g_rec.end()) return EBADF;
    rf = it->second;
  }
  if (i < 0 || (size_t)i >= rf->offsets.size()) return EINVAL;
  uint64_t off = rf->offsets[i], len = rf->sizes[i], done = 0;
  char* p = static_cast<char*>(dst);
  while (done < len) {
    ssize_t r = ::pread(rf->fd, p + done, len - done, off + done);
    if (r <= 0) return r < 0 ? errno : EIO;
    done += (uint64_t)r;
  }
  return 0;
}

// Parallel batch read: records idxs[0..n) land back-to-back in dst at
// dst_offsets[k] (caller computes the packing). 0 on success.
int prec_read_many(int64_t h, const int64_t* idxs, int n, void* dst,
                   const uint64_t* dst_offsets, int nthreads) {
  RecordFile* rf;
  {
    std::lock_guard<std::mutex> lk(g_rec_mu);
    auto it = g_rec.find(h);
    if (it == g_rec.end()) return EBADF;
    rf = it->second;
  }
  std::atomic<int> err{0};
  char* base = static_cast<char*>(dst);
  pool(nthreads)->parallel_for((size_t)n, [&](size_t k) {
    int64_t i = idxs[k];
    if (i < 0 || (size_t)i >= rf->offsets.size()) { err.store(EINVAL); return; }
    uint64_t off = rf->offsets[i], len = rf->sizes[i], done = 0;
    char* p = base + dst_offsets[k];
    while (done < len) {
      ssize_t r = ::pread(rf->fd, p + done, len - done, off + done);
      if (r <= 0) { err.store(r < 0 ? errno : EIO); return; }
      done += (uint64_t)r;
    }
  });
  return err.load();
}

void prec_close(int64_t h) {
  std::lock_guard<std::mutex> lk(g_rec_mu);
  auto it = g_rec.find(h);
  if (it != g_rec.end()) {
    ::close(it->second->fd);
    delete it->second;
    g_rec.erase(it);
  }
}

}  // extern "C"
