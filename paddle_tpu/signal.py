"""paddle.signal parity: stft / istft (ref: python/paddle/signal.py (U)).

TPU-native: framing is a gather into [*, n_frames, n_fft] followed by a batched
rfft — static shapes throughout, so the whole transform jits onto the MXU/VPU
with XLA picking the FFT codegen.
"""

from __future__ import annotations

import jax.numpy as jnp

from .core.op_call import apply
from .core.tensor import Tensor
from .tensor.creation import _as_t


def _frame(x, frame_length, hop_length):
    """[..., T] -> [..., n_frames, frame_length] via static gather."""
    t = x.shape[-1]
    if t < frame_length:
        raise ValueError(
            f"input length {t} is shorter than frame length {frame_length}; "
            f"pad the signal or use center=True")
    n_frames = 1 + (t - frame_length) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return x[..., idx]


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform, paddle signature: returns
    [..., n_fft//2+1 (or n_fft), n_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    xt = _as_t(x)
    win = None if window is None else _as_t(window)

    def f(a, *w):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        frames = _frame(a, n_fft, hop_length)  # [..., n_frames, n_fft]
        if w:
            wv = w[0]
            if win_length < n_fft:  # center the window inside the fft size
                lp = (n_fft - win_length) // 2
                wv = jnp.pad(wv, (lp, n_fft - win_length - lp))
            frames = frames * wv
        sp = jnp.fft.rfft(frames, axis=-1) if onesided else jnp.fft.fft(frames, axis=-1)
        if normalized:
            sp = sp / jnp.sqrt(jnp.asarray(n_fft, sp.real.dtype))
        return jnp.swapaxes(sp, -1, -2)  # [..., freq, n_frames]

    args = (xt,) + ((win,) if win is not None else ())
    return apply(f, *args, _op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with overlap-add and window-envelope normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    xt = _as_t(x)
    win = None if window is None else _as_t(window)

    def f(sp, *w):
        sp = jnp.swapaxes(sp, -1, -2)  # [..., n_frames, freq]
        if normalized:
            sp = sp * jnp.sqrt(jnp.asarray(n_fft, sp.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(sp, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(sp, axis=-1)
            if not return_complex:
                frames = frames.real
        if w:
            wv = w[0]
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                wv = jnp.pad(wv, (lp, n_fft - win_length - lp))
        else:
            wv = jnp.ones((n_fft,), frames.dtype)
        frames = frames * wv
        n_frames = frames.shape[-2]
        t = n_fft + hop_length * (n_frames - 1)
        # overlap-add via scatter-add over static indices
        starts = jnp.arange(n_frames) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :]).reshape(-1)
        flat = frames.reshape(frames.shape[:-2] + (-1,))
        out = jnp.zeros(frames.shape[:-2] + (t,), frames.dtype)
        out = out.at[..., idx].add(flat)
        env = jnp.zeros((t,), frames.dtype).at[idx].add(
            jnp.tile(wv * wv, n_frames))
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: t - n_fft // 2]
        if length is not None:
            if out.shape[-1] < length:  # tail lost to partial-frame trunc
                pad = [(0, 0)] * (out.ndim - 1) + [(0, length - out.shape[-1])]
                out = jnp.pad(out, pad)
            out = out[..., :length]
        return out

    args = (xt,) + ((win,) if win is not None else ())
    return apply(f, *args, _op_name="istft")
