"""LLaMA model family (BASELINE.md: LLaMA-2-13B stage-3+recompute config).

The decoder recipe (pre-norm RMSNorm, RoPE, SwiGLU, optional GQA) is shared
with the flagship implementation in models/gpt.py; this module gives it the
LLaMA naming plus the standard config presets so users of the reference's
ecosystem (PaddleNLP `LlamaForCausalLM`) find the same surface here.

Because the attention layer is shared, LlamaAttention accepts the serving
subsystem's cache views (the paged-pool `PagedKV` block-table view and
the slotted static-shape `SlotKV`) anywhere the legacy `(k, v)` concat
cache is accepted — a LlamaForCausalLM drops straight into
paddle_tpu.serving.Engine:

    from paddle_tpu.serving import Engine, EngineConfig
    engine = Engine(LlamaForCausalLM(LLAMA2_7B), EngineConfig(...))
"""

from .gpt import (
    LLAMA2_13B,
    GPTConfig as LlamaConfig,
    GPTAttention as LlamaAttention,
    GPTMLP as LlamaMLP,
    GPTDecoderLayer as LlamaDecoderLayer,
    GPTModel as LlamaModel,
    GPTForCausalLM as LlamaForCausalLM,
)

LLAMA2_7B = LlamaConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=11008,
    num_hidden_layers=32, num_attention_heads=32,
    max_position_embeddings=4096,
)
# LLaMA-3-style GQA preset (8 kv heads) — exercises the grouped-query path
LLAMA3_8B = LlamaConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336,
    num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
    max_position_embeddings=8192, rope_theta=500000.0,
)

__all__ = [
    "LlamaConfig", "LlamaAttention", "LlamaMLP", "LlamaDecoderLayer",
    "LlamaModel", "LlamaForCausalLM",
    "LLAMA2_7B", "LLAMA2_13B", "LLAMA3_8B",
]
