"""BERT family (BASELINE.md config 2: BERT-base MLM pretrain; the reference
hosts this in PaddleNLP). Encoder built from paddle_tpu.nn.TransformerEncoder
so attention rides the same flash path."""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding, Dropout
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer
from ..nn.layer.activation import GELU, Tanh
from ..nn import functional as F
from ..nn.initializer import Normal
from ..core.tensor import Tensor
from ..tensor import manipulation as M


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0


BERT_BASE = BertConfig()
BERT_LARGE = BertConfig(hidden_size=1024, num_hidden_layers=24,
                        num_attention_heads=16, intermediate_size=4096)


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        init = Normal(0.0, c.initializer_range)
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(c.max_position_embeddings, c.hidden_size, weight_attr=init)
        self.token_type_embeddings = Embedding(c.type_vocab_size, c.hidden_size, weight_attr=init)
        self.layer_norm = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = Tensor(jnp.broadcast_to(jnp.arange(s)[None, :], (b, s)))
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertPooler(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.dense = Linear(c.hidden_size, c.hidden_size)
        self.activation = Tanh()

    def forward(self, hidden_states):
        return self.activation(self.dense(hidden_states[:, 0]))


class BertModel(Layer):
    def __init__(self, config: BertConfig = None, with_pool=True):
        super().__init__()
        c = config or BertConfig()
        self.config = c
        self.embeddings = BertEmbeddings(c)
        enc_layer = TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation=c.hidden_act,
            attn_dropout=c.attention_probs_dropout_prob,
            layer_norm_eps=c.layer_norm_eps,
        )
        self.encoder = TransformerEncoder(enc_layer, c.num_hidden_layers)
        self.pooler = BertPooler(c) if with_pool else None

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # [B,S] 1/0 -> additive [B,1,1,S]
            m = attention_mask._data if isinstance(attention_mask, Tensor) else attention_mask
            mask = Tensor(((1.0 - m[:, None, None, :]) * -1e30).astype(jnp.float32))
        seq = self.encoder(x, mask)
        pooled = self.pooler(seq) if self.pooler is not None else None
        return seq, pooled


class BertLMPredictionHead(Layer):
    def __init__(self, c: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = Linear(c.hidden_size, c.hidden_size)
        self.activation = GELU()
        self.layer_norm = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.decoder_weight = embedding_weights  # tied
        self.decoder_bias = self.create_parameter([c.vocab_size], is_bias=True)

    def forward(self, x):
        x = self.layer_norm(self.activation(self.transform(x)))
        from ..tensor.math import matmul

        return matmul(x, M.t(self.decoder_weight)) + self.decoder_bias


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig = None):
        super().__init__()
        c = config or BertConfig()
        self.config = c
        self.bert = BertModel(c, with_pool=False)
        self.cls = BertLMPredictionHead(c, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.cls(seq)
        if labels is not None:
            loss = F.cross_entropy(
                M.reshape(logits, [-1, self.config.vocab_size]),
                M.reshape(labels, [-1]),
                ignore_index=-100,
            )
            return loss, logits
        return logits


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig = None, num_classes=2):
        super().__init__()
        c = config or BertConfig()
        self.bert = BertModel(c, with_pool=True)
        self.dropout = Dropout(c.hidden_dropout_prob)
        self.classifier = Linear(c.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return loss, logits
        return logits
