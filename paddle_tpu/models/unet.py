"""Stable-Diffusion-style conditional UNet (BASELINE.md config 5; the
reference hosts it in ppdiffusers). Kept at SD-1.x topology but
parameterized so the bench can scale it.

TPU-first layout (r4): the model runs CHANNELS-LAST (NHWC) internally —
the r4 device trace (benchmarks/profiles/unet_b4_r4.json) showed the
NCHW variant spending 50% of device time in data-formatting ops (2387
transposes/step, 80% HBM-bound) because every TransformerBlock2D hop
between conv [B,C,H,W] and attention [B,HW,C] materializes a physical
transpose. With C already minor, those hops are free reshapes. The
weight layout (OIHW, paddle convention) and the state_dict are
unchanged; `channels_last=False` restores the reference layout
bit-for-bit (parity-tested in tests/test_models.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Dropout, Upsample
from ..nn.layer.conv import Conv2D
from ..nn.layer.norm import GroupNorm, LayerNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..core.tensor import Tensor
from ..tensor import manipulation as M


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: tuple = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: int = 8
    norm_num_groups: int = 32
    sample_size: int = 64
    channels_last: bool = True


def timestep_embedding(t, dim, max_period=10000):
    half = dim // 2
    freqs = jnp.exp(-jnp.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


class ResnetBlock2D(Layer):
    def __init__(self, in_c, out_c, temb_c, groups=32, data_format="NCHW"):
        super().__init__()
        self._df = data_format
        self.norm1 = GroupNorm(min(groups, in_c), in_c, data_format=data_format)
        self.conv1 = Conv2D(in_c, out_c, 3, padding=1, data_format=data_format)
        self.time_emb_proj = Linear(temb_c, out_c)
        self.norm2 = GroupNorm(min(groups, out_c), out_c, data_format=data_format)
        self.conv2 = Conv2D(out_c, out_c, 3, padding=1, data_format=data_format)
        self.shortcut = Conv2D(in_c, out_c, 1, data_format=data_format) \
            if in_c != out_c else None

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        tshape = ([temb.shape[0], 1, 1, -1] if self._df == "NHWC"
                  else [temb.shape[0], -1, 1, 1])
        h = h + M.reshape(self.time_emb_proj(F.silu(temb)), tshape)
        h = self.conv2(F.silu(self.norm2(h)))
        sc = self.shortcut(x) if self.shortcut is not None else x
        return h + sc


class CrossAttention(Layer):
    def __init__(self, query_dim, context_dim, heads):
        super().__init__()
        self.heads = heads
        self.head_dim = query_dim // heads
        self.to_q = Linear(query_dim, query_dim, bias_attr=False)
        self.to_k = Linear(context_dim, query_dim, bias_attr=False)
        self.to_v = Linear(context_dim, query_dim, bias_attr=False)
        self.to_out = Linear(query_dim, query_dim)

    def forward(self, x, context=None):
        b, s, _ = x.shape
        if context is None:
            # self-attention: ONE [D, 3D] GEMM (r5 — same in-trace weight
            # concat as nn.MultiHeadAttention; state_dict unchanged)
            w = M.concat([self.to_q.weight, self.to_k.weight,
                          self.to_v.weight], axis=1)
            qkv = M.reshape(F.linear(x, w),
                            [b, s, 3, self.heads, self.head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            sk = context.shape[1]
            q = M.reshape(self.to_q(x), [b, s, self.heads, self.head_dim])
            # cross-attention: K/V share the context — one [C, 2D] GEMM
            wkv = M.concat([self.to_k.weight, self.to_v.weight], axis=1)
            kv = M.reshape(F.linear(context, wkv),
                           [b, sk, 2, self.heads, self.head_dim])
            k, v = kv[:, :, 0], kv[:, :, 1]
        out = F.scaled_dot_product_attention(q, k, v, training=self.training)
        return self.to_out(M.reshape(out, [b, s, self.heads * self.head_dim]))


class TransformerBlock2D(Layer):
    def __init__(self, dim, context_dim, heads, groups=32,
                 data_format="NCHW"):
        super().__init__()
        self._df = data_format
        self.norm_in = GroupNorm(min(groups, dim), dim, data_format=data_format)
        self.proj_in = Conv2D(dim, dim, 1, data_format=data_format)
        self.norm1 = LayerNorm(dim)
        self.attn1 = CrossAttention(dim, dim, heads)
        self.norm2 = LayerNorm(dim)
        self.attn2 = CrossAttention(dim, context_dim, heads)
        self.norm3 = LayerNorm(dim)
        self.ff1 = Linear(dim, dim * 4)
        self.ff2 = Linear(dim * 4, dim)
        self.proj_out = Conv2D(dim, dim, 1, data_format=data_format)

    def forward(self, x, context):
        residual = x
        y = self.proj_in(self.norm_in(x))
        if self._df == "NHWC":
            # channels already minor: [B,H,W,C] <-> [B,HW,C] is a free
            # reshape — the whole point of the channels-last layout
            b, h, w, c = x.shape
            y = M.reshape(y, [b, h * w, c])
        else:
            b, c, h, w = x.shape
            y = M.reshape(M.transpose(y, [0, 2, 3, 1]), [b, h * w, c])
        y = y + self.attn1(self.norm1(y))
        y = y + self.attn2(self.norm2(y), context)
        y = y + self.ff2(F.gelu(self.ff1(self.norm3(y))))
        if self._df == "NHWC":
            y = M.reshape(y, [b, h, w, c])
        else:
            y = M.transpose(M.reshape(y, [b, h, w, c]), [0, 3, 1, 2])
        return self.proj_out(y) + residual


class Downsample2D(Layer):
    def __init__(self, c, data_format="NCHW"):
        super().__init__()
        self.conv = Conv2D(c, c, 3, stride=2, padding=1,
                           data_format=data_format)

    def forward(self, x):
        return self.conv(x)


class Upsample2D(Layer):
    def __init__(self, c, data_format="NCHW"):
        super().__init__()
        self._df = data_format
        self.conv = Conv2D(c, c, 3, padding=1, data_format=data_format)

    def forward(self, x):
        x = F.interpolate(x, scale_factor=2, mode="nearest",
                          data_format=self._df)
        return self.conv(x)


class UNet2DConditionModel(Layer):
    def __init__(self, config: UNetConfig = None):
        super().__init__()
        c = config or UNetConfig()
        self.config = c
        df = "NHWC" if getattr(c, "channels_last", False) else "NCHW"
        self._df = df
        ch = c.block_out_channels
        temb_c = ch[0] * 4
        self.conv_in = Conv2D(c.in_channels, ch[0], 3, padding=1,
                              data_format=df)
        self.time_proj_dim = ch[0]
        self.time_mlp1 = Linear(ch[0], temb_c)
        self.time_mlp2 = Linear(temb_c, temb_c)

        heads = c.attention_head_dim

        # down
        self.down_resnets = LayerList()
        self.down_attns = LayerList()
        self.downsamplers = LayerList()
        self._down_plan = []
        in_c = ch[0]
        for i, out_c in enumerate(ch):
            use_attn = i < len(ch) - 1  # SD: attn on all but the last down block
            for j in range(c.layers_per_block):
                self.down_resnets.append(ResnetBlock2D(
                    in_c, out_c, temb_c, c.norm_num_groups, data_format=df))
                self.down_attns.append(
                    TransformerBlock2D(out_c, c.cross_attention_dim, heads,
                                       c.norm_num_groups, data_format=df)
                    if use_attn else _Identity()
                )
                self._down_plan.append(use_attn)
                in_c = out_c
            if i < len(ch) - 1:
                self.downsamplers.append(Downsample2D(out_c, data_format=df))

        # mid
        self.mid_res1 = ResnetBlock2D(ch[-1], ch[-1], temb_c,
                                      c.norm_num_groups, data_format=df)
        self.mid_attn = TransformerBlock2D(ch[-1], c.cross_attention_dim,
                                           heads, c.norm_num_groups,
                                           data_format=df)
        self.mid_res2 = ResnetBlock2D(ch[-1], ch[-1], temb_c,
                                      c.norm_num_groups, data_format=df)

        # up
        self.up_resnets = LayerList()
        self.up_attns = LayerList()
        self.upsamplers = LayerList()
        self._up_plan = []
        rev = list(reversed(ch))
        prev_c = ch[-1]
        for i, out_c in enumerate(rev):
            use_attn = i > 0
            skip_ch_list = self._skip_channels(ch, i, c.layers_per_block)
            for j in range(c.layers_per_block + 1):
                skip_c = skip_ch_list[j]
                self.up_resnets.append(ResnetBlock2D(
                    prev_c + skip_c, out_c, temb_c, c.norm_num_groups,
                    data_format=df))
                self.up_attns.append(
                    TransformerBlock2D(out_c, c.cross_attention_dim, heads,
                                       c.norm_num_groups, data_format=df)
                    if use_attn else _Identity()
                )
                self._up_plan.append(use_attn)
                prev_c = out_c
            if i < len(rev) - 1:
                self.upsamplers.append(Upsample2D(out_c, data_format=df))

        self.conv_norm_out = GroupNorm(c.norm_num_groups, ch[0],
                                       data_format=df)
        self.conv_out = Conv2D(ch[0], c.out_channels, 3, padding=1,
                               data_format=df)

    @staticmethod
    def _skip_channels(ch, up_idx, layers_per_block):
        """Channels of skip connections consumed by up-block `up_idx`."""
        # build the stack the down path produces
        stack = [ch[0]]
        for i, out_c in enumerate(ch):
            for _ in range(layers_per_block):
                stack.append(out_c)
            if i < len(ch) - 1:
                stack.append(out_c)
        # up blocks pop layers_per_block+1 each, in reverse
        start = len(stack) - (up_idx * (layers_per_block + 1))
        return [stack[start - 1 - j] for j in range(layers_per_block + 1)]

    def forward(self, sample, timestep, encoder_hidden_states):
        temb_raw = timestep_embedding(
            timestep._data if isinstance(timestep, Tensor) else jnp.asarray(timestep),
            self.time_proj_dim,
        )
        # sinusoidal embedding is f32; follow the model's compute dtype so a
        # bf16-cast model stays bf16 end to end
        temb_raw = temb_raw.astype(self.time_mlp1.weight._data.dtype)
        temb = self.time_mlp2(F.silu(self.time_mlp1(Tensor(temb_raw))))

        if self._df == "NHWC":
            # one boundary transpose each way; everything inside is
            # channels-last so conv<->attention hops are free reshapes
            sample = M.transpose(sample, [0, 2, 3, 1])
        x = self.conv_in(sample)
        skips = [x]
        ri = 0
        di = 0
        ch = self.config.block_out_channels
        for i in range(len(ch)):
            for j in range(self.config.layers_per_block):
                x = self.down_resnets[ri](x, temb)
                if self._down_plan[ri]:
                    x = self.down_attns[ri](x, encoder_hidden_states)
                skips.append(x)
                ri += 1
            if i < len(ch) - 1:
                x = self.downsamplers[di](x)
                skips.append(x)
                di += 1

        x = self.mid_res1(x, temb)
        x = self.mid_attn(x, encoder_hidden_states)
        x = self.mid_res2(x, temb)

        ri = 0
        ui = 0
        for i in range(len(ch)):
            for j in range(self.config.layers_per_block + 1):
                skip = skips.pop()
                x = M.concat([x, skip],
                             axis=-1 if self._df == "NHWC" else 1)
                x = self.up_resnets[ri](x, temb)
                if self._up_plan[ri]:
                    x = self.up_attns[ri](x, encoder_hidden_states)
                ri += 1
            if i < len(ch) - 1:
                x = self.upsamplers[ui](x)
                ui += 1

        x = F.silu(self.conv_norm_out(x))
        x = self.conv_out(x)
        if self._df == "NHWC":
            x = M.transpose(x, [0, 3, 1, 2])
        return x


class _Identity(Layer):
    def forward(self, x, *a, **k):
        return x
