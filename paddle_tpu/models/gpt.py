"""Decoder-only transformer LM family — the ERNIE-3.5 / LLaMA-2 capability
target (BASELINE.md configs). The reference keeps these in PaddleNLP
(ecosystem); the TPU build ships them in-repo as the flagship models.

TPU-first design decisions:
  * pre-norm RMSNorm + RoPE + SwiGLU (LLaMA recipe, which ERNIE-3.5-class
    models follow) — all shapes static, seq-major-free [B, S, H, D]
  * attention through F.scaled_dot_product_attention → Pallas flash kernel
  * every Parameter carries a `sharding_axes` hint consumed by the fleet
    layer to build pjit shardings: ('mp' on ffn/vocab dims, None elsewhere)
  * no Python-level KV-cache branching inside the train path — decode uses a
    separate cache path, so the training graph stays branch-free for XLA.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding, Dropout
from ..nn.layer.norm import RMSNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..nn.initializer import Normal
from ..core.tensor import Tensor
from ..tensor import manipulation as M
from ..ops.rope import apply_rotary_emb


@dataclass
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    intermediate_size: int = 2048
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    num_key_value_heads: int = None  # GQA; defaults to MHA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    hidden_dropout_prob: float = 0.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    use_recompute: bool = False
    # fused chunked LM-head CE: never materialises [B*S, vocab] f32 logits
    # (forward(labels=...) then returns (loss, None))
    fused_lm_loss: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self):
        return self.num_key_value_heads or self.num_attention_heads


# BASELINE.md model configs
ERNIE_7B = GPTConfig(
    vocab_size=32000, hidden_size=4096, intermediate_size=11008,
    num_hidden_layers=32, num_attention_heads=32, max_position_embeddings=4096,
)
LLAMA2_13B = GPTConfig(
    vocab_size=32000, hidden_size=5120, intermediate_size=13824,
    num_hidden_layers=40, num_attention_heads=40, max_position_embeddings=4096,
)


def _mark(p, axes):
    """Attach a PartitionSpec-style sharding hint, consumed by fleet/pjit."""
    p._sharding_axes = axes
    return p


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.kv_heads = c.kv_heads
        self.head_dim = c.head_dim
        self.rope_theta = c.rope_theta
        init = Normal(0.0, c.initializer_range)
        self.q_proj = Linear(c.hidden_size, self.num_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.k_proj = Linear(c.hidden_size, self.kv_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.v_proj = Linear(c.hidden_size, self.kv_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, c.hidden_size,
                             weight_attr=init, bias_attr=False)
        # TP sharding hints: column-parallel qkv, row-parallel out
        _mark(self.q_proj.weight, (None, "mp"))
        _mark(self.k_proj.weight, (None, "mp"))
        _mark(self.v_proj.weight, (None, "mp"))
        _mark(self.o_proj.weight, ("mp", None))

    def forward(self, x, attn_mask=None, cache=None, position_offset=0):
        b, s, _ = x.shape
        q = M.reshape(self.q_proj(x), [b, s, self.num_heads, self.head_dim])
        k = M.reshape(self.k_proj(x), [b, s, self.kv_heads, self.head_dim])
        v = M.reshape(self.v_proj(x), [b, s, self.kv_heads, self.head_dim])
        import numpy as np

        if cache is not None and not isinstance(cache, (tuple, list)):
            if hasattr(cache, "tables"):
                # serving paged path: PagedKV — scatter this chunk's k/v
                # into table-mapped pool blocks, ragged paged attention
                # reads only live blocks (paddle_tpu.serving).
                return self._forward_paged(q, k, v, cache, b, s)
            # serving path: SlotKV slotted static-shape cache — per-row
            # positions, dynamic_update_slice writes, full-length masked
            # attention. One compiled decode step serves every request
            # mix (paddle_tpu.serving); the tuple branch below stays the
            # legacy concat-per-step cache.
            return self._forward_slotted(q, k, v, cache, b, s)

        pos = None
        if position_offset:
            pos_ids = jnp.arange(position_offset, position_offset + s)[None, :]
            pos = Tensor(jnp.broadcast_to(pos_ids, (b, s)))
        q = apply_rotary_emb(q, position_ids=pos, base=self.rope_theta)
        k = apply_rotary_emb(k, position_ids=pos, base=self.rope_theta)
        if cache is not None:
            if cache[0] is not None:
                k = M.concat([cache[0], k], axis=1)
                v = M.concat([cache[1], v], axis=1)
            new_cache = (k, v)
        else:
            new_cache = None
        # GQA: kv heads stay narrow — the flash kernel shares them across
        # query groups via its BlockSpec index map; the XLA fallback repeats
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=True, training=self.training)
        out = self.o_proj(M.reshape(out, [b, s, self.num_heads * self.head_dim]))
        if cache is not None:
            return out, new_cache
        return out

    def _forward_slotted(self, q, k, v, cache, b, s):
        """Slotted-cache attention: write this chunk's k/v into the cache
        rows at the per-row positions, attend over the full static-length
        buffers under a validity mask. Bit-compatible with the concat
        path — the same rope/attention math over the same valid keys,
        with masked positions contributing exp(-inf) = 0."""
        import jax.numpy as jnp

        from ..serving.kv_cache import SlotKV, visible_mask, write_slots

        pos = cache.pos
        pos_ids = Tensor(pos[:, None]
                         + jnp.arange(s, dtype=pos.dtype)[None, :])
        q = apply_rotary_emb(q, position_ids=pos_ids, base=self.rope_theta)
        k = apply_rotary_emb(k, position_ids=pos_ids, base=self.rope_theta)
        k_all = write_slots(cache.k, k._data, pos)
        v_all = write_slots(cache.v, v._data, pos)
        mask = visible_mask(pos, s, cache.max_seq_len)
        out = F.scaled_dot_product_attention(
            q, Tensor(k_all), Tensor(v_all), attn_mask=Tensor(mask),
            is_causal=False, training=self.training)
        out = self.o_proj(M.reshape(out, [b, s, self.num_heads * self.head_dim]))
        return out, SlotKV(k_all, v_all, pos + s)

    def _forward_paged(self, q, k, v, cache, b, s):
        """Paged-cache attention: rope at the per-row positions, scatter
        k/v into the lane's table-mapped pool blocks (write-before-attend
        so the current token's keys are visible to itself), then ragged
        paged attention over the block table — only blocks below each
        lane's length are read. Bitwise-compatible with the slotted path:
        same rope/attention math over the same visible keys. A quantized
        pool (cache.k_scale set) quantizes each token at the write and
        dequantizes gathered blocks inside paged attention — same math
        over dequantized values, so parity within a quant config holds."""
        import jax.numpy as jnp

        from ..serving.kv_cache import PagedKV, paged_write, paged_write_quant
        from ..serving.paged_attention import paged_attention

        pos = cache.pos
        pos_ids = Tensor(pos[:, None]
                         + jnp.arange(s, dtype=pos.dtype)[None, :])
        q = apply_rotary_emb(q, position_ids=pos_ids, base=self.rope_theta)
        k = apply_rotary_emb(k, position_ids=pos_ids, base=self.rope_theta)
        if cache.k_scale is not None:
            k_pool, k_scale = paged_write_quant(
                cache.k, cache.k_scale, k._data, cache.tables, pos)
            v_pool, v_scale = paged_write_quant(
                cache.v, cache.v_scale, v._data, cache.tables, pos)
        else:
            k_pool = paged_write(cache.k, k._data, cache.tables, pos)
            v_pool = paged_write(cache.v, v._data, cache.tables, pos)
            k_scale = v_scale = None
        out = paged_attention(q._data, k_pool, v_pool, cache.tables, pos,
                              k_scale, v_scale)
        out = self.o_proj(M.reshape(Tensor(out),
                                    [b, s, self.num_heads * self.head_dim]))
        return out, PagedKV(k_pool, v_pool, cache.tables, pos + s,
                            k_scale, v_scale)


class GPTMLP(Layer):
    """SwiGLU feed-forward."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        init = Normal(0.0, c.initializer_range)
        self.gate_proj = Linear(c.hidden_size, c.intermediate_size, weight_attr=init, bias_attr=False)
        self.up_proj = Linear(c.hidden_size, c.intermediate_size, weight_attr=init, bias_attr=False)
        self.down_proj = Linear(c.intermediate_size, c.hidden_size, weight_attr=init, bias_attr=False)
        _mark(self.gate_proj.weight, (None, "mp"))
        _mark(self.up_proj.weight, (None, "mp"))
        _mark(self.down_proj.weight, ("mp", None))

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class GPTDecoderLayer(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.self_attn = GPTAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.mlp = GPTMLP(config)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, x, attn_mask=None, cache=None, position_offset=0):
        residual = x
        h = self.input_layernorm(x)
        if cache is not None:
            h, new_cache = self.self_attn(h, attn_mask, cache, position_offset)
        else:
            h = self.self_attn(h, attn_mask)
            new_cache = None
        x = residual + self.dropout(h)
        residual = x
        h = self.mlp(self.post_attention_layernorm(x))
        x = residual + self.dropout(h)
        if cache is not None:
            return x, new_cache
        return x


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=Normal(0.0, config.initializer_range))
        _mark(self.embed_tokens.weight, ("mp", None))  # vocab-parallel
        self.layers = LayerList([GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, caches=None, position_offset=0):
        x = self.embed_tokens(input_ids)
        new_caches = [] if caches is not None else None
        for i, layer in enumerate(self.layers):
            if self.config.use_recompute and self.training and caches is None:
                from ..distributed.recompute import recompute

                x = recompute(layer, x, attn_mask)
            elif caches is not None:
                x, nc = layer(x, attn_mask, caches[i], position_offset)
                new_caches.append(nc)
            else:
                x = layer(x, attn_mask)
        x = self.norm(x)
        if caches is not None:
            return x, new_caches
        return x


class GPTForCausalLM(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.model = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=Normal(0.0, config.initializer_range),
                                  bias_attr=False)
            _mark(self.lm_head.weight, (None, "mp"))

    def _logits(self, h):
        if self.lm_head is not None:
            return self.lm_head(h)
        from ..tensor.math import matmul

        return matmul(h, M.t(self.model.embed_tokens.weight))

    def forward(self, input_ids, labels=None, attn_mask=None):
        h = self.model(input_ids, attn_mask)
        if labels is not None and self.config.fused_lm_loss:
            from ..incubate.nn.functional import fused_linear_cross_entropy

            hidden = M.reshape(h, [-1, self.config.hidden_size])
            flat_labels = M.reshape(labels, [-1])
            if self.lm_head is not None:
                loss = fused_linear_cross_entropy(
                    hidden, self.lm_head.weight, flat_labels,
                    ignore_index=-100)
            else:  # tied embeddings: weight is [vocab, hidden]
                loss = fused_linear_cross_entropy(
                    hidden, self.model.embed_tokens.weight, flat_labels,
                    ignore_index=-100, transpose_weight=True)
            return loss, None
        logits = self._logits(h)
        if labels is not None:
            loss = F.cross_entropy(
                M.reshape(logits, [-1, self.config.vocab_size]),
                M.reshape(labels, [-1]),
                ignore_index=-100,
            )
            return loss, logits
        return logits

    # -------- decode --------
    def generate(self, input_ids, max_new_tokens=20, temperature=1.0, top_k=0):
        import numpy as np

        import paddle_tpu as paddle

        self.eval()
        from ..core import tape as _tape

        with _tape.no_grad():
            b, s = input_ids.shape
            h, caches = self.model(input_ids, caches=[(None, None)] * len(self.model.layers))
            out_ids = [input_ids]
            last = input_ids[:, -1:]
            logits = self._logits(h)[:, -1]
            for step in range(max_new_tokens):
                if temperature == 0:
                    nxt = paddle.argmax(logits, axis=-1).unsqueeze(-1)
                else:
                    probs = F.softmax(logits / temperature, axis=-1)
                    nxt = paddle.multinomial(probs, 1)
                out_ids.append(nxt)
                h, caches = self.model(nxt, caches=caches, position_offset=s + step)
                logits = self._logits(h)[:, -1]
            return M.concat(out_ids, axis=1)
