"""Model zoo (the PaddleNLP/ppdiffusers-analog families, in-repo since the
TPU build is self-contained): transformer LMs (ERNIE/LLaMA-style), BERT,
and the diffusion UNet."""

from . import gpt
from . import bert
from . import unet
from . import llama
from .gpt import GPTConfig, GPTModel, GPTForCausalLM, ERNIE_7B, LLAMA2_13B
from .bert import BertConfig, BertModel, BertForMaskedLM
from .unet import UNetConfig, UNet2DConditionModel
from .llama import (
    LlamaConfig, LlamaModel, LlamaForCausalLM,
    LLAMA2_7B, LLAMA3_8B,
)
