"""Random sampling ops (ref: python/paddle/tensor/random.py (U)).

Stateful-looking API over jax's functional PRNG: each call pulls a fresh key
from the global counter stream (core/random.py), which jit.to_static threads
through compiled programs as a traced argument.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dtype import to_jax_dtype, get_default_dtype
from ..core import random_state
from ..core.op_call import apply
from .creation import _shape, _as_t


def _dt(dtype, default=None):
    return to_jax_dtype(dtype) if dtype is not None else (default or get_default_dtype())


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(random_state.next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(random_state.next_key(), _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(getattr(m, "shape", ()), getattr(s, "shape", ()))
        return Tensor(jax.random.normal(random_state.next_key(), shp) * s + m)
    shp = _shape(shape) if shape is not None else ()
    return Tensor(jax.random.normal(random_state.next_key(), shp) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else random_state.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype), minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(random_state.next_key(), tuple(x.shape), x.dtype, minval=min, maxval=max)
    return x


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(random_state.next_key(), _shape(shape), low, high, dtype=_dt(dtype, jnp.int32)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = _as_t(x)
    return randint(low, high, tuple(x.shape), dtype or str(x.dtype))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(random_state.next_key(), n).astype(_dt(dtype, jnp.int32)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = _as_t(x)

    def f(a, key):
        logits = jnp.log(jnp.maximum(a, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, axis=-1, shape=(num_samples,) if a.ndim == 1 else (a.shape[0], num_samples)).T if False else (
                jax.random.categorical(key, logits[None] if a.ndim == 1 else logits, axis=-1,
                                       shape=(num_samples, 1) if a.ndim == 1 else (num_samples, a.shape[0]))
            )
        # without replacement: gumbel top-k
        g = jax.random.gumbel(key, a.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx

    key = random_state.next_key()
    if replacement:
        logits = jnp.log(jnp.maximum(x._data, 1e-30))
        if x.ndim == 1:
            out = jax.random.categorical(key, logits, shape=(num_samples,))
        else:
            out = jax.random.categorical(key, logits[:, None, :], axis=-1, shape=(x.shape[0], num_samples))
        return Tensor(out.astype(jnp.int32))
    g = jax.random.gumbel(key, tuple(x.shape))
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return Tensor(idx.astype(jnp.int32))


def bernoulli(x, name=None):
    x = _as_t(x)
    return Tensor(jax.random.bernoulli(random_state.next_key(), x._data).astype(x.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(random_state.next_key(), p, tuple(x.shape)).astype(x.dtype)
    return x


def poisson(x, name=None):
    x = _as_t(x)
    return Tensor(jax.random.poisson(random_state.next_key(), x._data).astype(x.dtype))


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(random_state.next_key(), tuple(x.shape)) / lam).astype(x.dtype)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (jax.random.normal(random_state.next_key(), tuple(x.shape)) * std + mean).astype(x.dtype)
    return x


def rand_like(x, dtype=None, name=None):
    x = _as_t(x)
    return rand(tuple(x.shape), dtype or x.dtype)


def randn_like(x, dtype=None, name=None):
    x = _as_t(x)
    return randn(tuple(x.shape), dtype or x.dtype)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = _as_t(x)
    key = random_state.next_key()

    def f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), a.shape[axis], dtype=a.dtype, axis=axis)
            y = y_hard + y - jax.lax.stop_gradient(y)
        return y

    return apply(f, x, _op_name="gumbel_softmax")
