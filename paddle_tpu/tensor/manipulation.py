"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py (U)).

All static-shape ops here are jit-safe; the data-dependent ones (masked_select,
nonzero, unique) are eager-only — under `to_static` use their fixed-size
variants (where with fill, topk) as the reference's to_static guide also does.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.op_call import apply
from .creation import _as_t


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(s) for s in np.asarray(v._data).reshape(-1))
    if isinstance(v, (int, np.integer)):
        return int(v)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in v)


def reshape(x, shape, name=None):
    shape = _ints(shape)
    return apply(lambda a: jnp.reshape(a, shape), _as_t(x), _op_name="reshape")


def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, _ints(shape))
    return x


def transpose(x, perm, name=None):
    perm = _ints(perm)
    return apply(lambda a: jnp.transpose(a, perm), _as_t(x), _op_name="transpose")


def t(x, name=None):
    x = _as_t(x)
    if x.ndim < 2:
        return x.clone()
    return apply(lambda a: a.T, x, _op_name="t")


def moveaxis(x, source, destination, name=None):
    return apply(lambda a: jnp.moveaxis(a, source, destination), _as_t(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply(lambda a: jnp.swapaxes(a, axis0, axis1), _as_t(x))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _as_t(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0

    def f(a):
        shape = a.shape[:sa] + (-1,) + a.shape[ea + 1:]
        return jnp.reshape(a, shape)

    return apply(f, x, _op_name="flatten")


def squeeze(x, axis=None, name=None):
    if axis is not None:
        axis = _ints(axis)
        if isinstance(axis, int):
            axis = (axis,)
        axis = tuple(a for a in axis)

    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = tuple(a2 % a.ndim for a2 in axis if a.shape[a2 % a.ndim] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a

    return apply(f, _as_t(x), _op_name="squeeze")


def unsqueeze(x, axis, name=None):
    axis = _ints(axis)
    return apply(lambda a: jnp.expand_dims(a, axis), _as_t(x), _op_name="unsqueeze")


def concat(x, axis=0, name=None):
    ts = [_as_t(t) for t in x]
    ax = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda *xs: jnp.concatenate(xs, axis=ax), *ts, _op_name="concat")


def stack(x, axis=0, name=None):
    ts = [_as_t(t) for t in x]
    return apply(lambda *xs: jnp.stack(xs, axis=axis), *ts, _op_name="stack")


def split(x, num_or_sections, axis=0, name=None):
    x = _as_t(x)
    ax = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    n = x.shape[ax]
    if isinstance(num_or_sections, int):
        if n % num_or_sections != 0:
            raise ValueError(
                f"split: axis {ax} length {n} is not divisible by num_or_sections={num_or_sections}"
            )
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(sizes) if s < 0]
        if neg:
            sizes[neg[0]] = n - sum(s for s in sizes if s >= 0)
    offsets = np.cumsum([0] + sizes[:-1])

    def f(a):
        return tuple(lax.slice_in_dim(a, int(o), int(o + s), axis=ax) for o, s in zip(offsets, sizes))

    return list(apply(f, x, _op_name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = _as_t(x)
    n = x.shape[axis]
    outs = split(x, n, axis)
    return [squeeze(o, axis) for o in outs]


unstack = unbind


def tile(x, repeat_times, name=None):
    rt = _ints(repeat_times)
    return apply(lambda a: jnp.tile(a, rt), _as_t(x), _op_name="tile")


def expand(x, shape, name=None):
    shape = _ints(shape)
    x = _as_t(x)

    def f(a):
        tgt = list(shape)
        # paddle allows -1 meaning "keep this dim"
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off] if i >= off else 1
        return jnp.broadcast_to(a, tgt)

    return apply(f, x, _op_name="expand")


def expand_as(x, y, name=None):
    return apply(lambda a, b: jnp.broadcast_to(a, b.shape), _as_t(x), _as_t(y).detach(), _op_name="expand_as")


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    ts = [_as_t(t) for t in inputs]
    outs = apply(lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *ts)
    return list(outs)


def flip(x, axis, name=None):
    ax = _ints(axis)
    return apply(lambda a: jnp.flip(a, axis=ax), _as_t(x), _op_name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _as_t(x))


def roll(x, shifts, axis=None, name=None):
    sh = _ints(shifts)
    ax = _ints(axis) if axis is not None else None
    return apply(lambda a: jnp.roll(a, sh, axis=ax), _as_t(x), _op_name="roll")


def gather(x, index, axis=0, name=None):
    ax = int(axis._data) if isinstance(axis, Tensor) else int(axis)
    return apply(lambda a, i: jnp.take(a, i.astype(jnp.int32), axis=ax), _as_t(x), _as_t(index), _op_name="gather")


def gather_nd(x, index, name=None):
    def f(a, i):
        i = i.astype(jnp.int32)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]

    return apply(f, _as_t(x), _as_t(index), _op_name="gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.astype(jnp.int32).reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].set(0).at[i].add(u)

    return apply(f, _as_t(x), _as_t(index), _as_t(updates), _op_name="scatter")


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._data = out._data
    return x


def scatter_nd_add(x, index, updates, name=None):
    def f(a, i, u):
        i = i.astype(jnp.int32)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)

    return apply(f, _as_t(x), _as_t(index), _as_t(updates), _op_name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    base = zeros(shape, dtype=_as_t(updates).dtype)
    return scatter_nd_add(base, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    def f(a, i):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, i.astype(jnp.int32)]

    return apply(f, _as_t(x), _as_t(index), _op_name="index_sample")


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32)
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        return jnp.moveaxis(am.at[i].add(vm), 0, axis)

    return apply(f, _as_t(x), _as_t(index), _as_t(value), _op_name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        idx = tuple(i.astype(jnp.int32) if jnp.issubdtype(i.dtype, jnp.integer) else i for i in idx)
        return a.at[idx].add(v) if accumulate else a.at[idx].set(v)

    return apply(f, _as_t(x), _as_t(value), *[_as_t(i) for i in indices], _op_name="index_put")


def masked_select(x, mask, name=None):
    # data-dependent shape: eager only
    x, mask = _as_t(x), _as_t(mask)
    return Tensor(x._data[np.asarray(mask._data)])


def masked_fill(x, mask, value, name=None):
    v = value._data if isinstance(value, Tensor) else value
    return apply(lambda a, m: jnp.where(m, v, a), _as_t(x), _as_t(mask).detach(), _op_name="masked_fill")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply(lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis), _as_t(arr), _as_t(indices), _op_name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def f(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape) if not hasattr(v, "shape") or v.shape != i.shape else v
        dims = []
        for d in range(a.ndim):
            if d == axis:
                dims.append(i)
            else:
                shape = [1] * a.ndim
                shape[d] = a.shape[d]
                dims.append(jnp.broadcast_to(jnp.arange(a.shape[d]).reshape(shape), i.shape))
        idx = tuple(dims)
        if reduce == "add":
            return a.at[idx].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[idx].multiply(v)
        return a.at[idx].set(v)

    return apply(f, _as_t(arr), _as_t(indices), _as_t(values), _op_name="put_along_axis")


def take(x, index, mode="raise", name=None):
    m = {"raise": "clip", "wrap": "wrap", "clip": "clip"}[mode]
    return apply(lambda a, i: jnp.take(a.reshape(-1), i.astype(jnp.int32), mode=m), _as_t(x), _as_t(index))


def slice(input, axes, starts, ends, name=None):
    axes = _ints(axes)
    starts = _ints(starts)
    ends = _ints(ends)

    def f(a):
        out = a
        for ax, st, en in zip(axes, starts, ends):
            n = a.shape[ax]
            st2 = max(st + n, 0) if st < 0 else min(st, n)
            en2 = max(en + n, 0) if en < 0 else min(en, n)
            out = lax.slice_in_dim(out, st2, en2, axis=ax)
        return out

    return apply(f, _as_t(input), _op_name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins

    axes, starts, ends, strides = _ints(axes), _ints(starts), _ints(ends), _ints(strides)

    def f(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(st, en, sd)
        return a[tuple(idx)]

    return apply(f, _as_t(x), _op_name="strided_slice")


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply(lambda a: jnp.repeat(a, r, axis=axis), _as_t(x), _op_name="repeat_interleave")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    # data-dependent: eager only
    x = _as_t(x)
    res = np.unique(
        np.asarray(x._data), return_index=return_index, return_inverse=return_inverse,
        return_counts=return_counts, axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(res)
    return tuple(Tensor(r) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = np.asarray(_as_t(x)._data)
    if axis is None:
        x = x.reshape(-1)
    keep = np.concatenate([[True], x[1:] != x[:-1]]) if x.ndim == 1 else None
    out = x[keep]
    rets = [Tensor(out)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        rets.append(Tensor(inv))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, len(x)))
        rets.append(Tensor(counts))
    return rets[0] if len(rets) == 1 else tuple(rets)


def nonzero(x, as_tuple=False, name=None):
    x = _as_t(x)
    nz = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(n) for n in nz)
    return Tensor(np.stack(nz, axis=-1).astype(np.int64))


def where(condition, x=None, y=None, name=None):
    cond = _as_t(condition)
    if x is None and y is None:
        return nonzero(cond, as_tuple=True)
    xv = x if isinstance(x, Tensor) else _as_t(x)
    yv = y if isinstance(y, Tensor) else _as_t(y)
    return apply(lambda c, a, b: jnp.where(c, a, b), cond.detach(), xv, yv, _op_name="where")


def as_complex(x, name=None):
    return apply(lambda a: lax.complex(a[..., 0], a[..., 1]), _as_t(x))


def as_real(x, name=None):
    return apply(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), _as_t(x))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return _as_t(x).astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, _as_t(other).shape)


def atleast_1d(*inputs, name=None):
    outs = [apply(jnp.atleast_1d, _as_t(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply(jnp.atleast_2d, _as_t(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply(jnp.atleast_3d, _as_t(t)) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = _ints(ax)
    return apply(lambda a, b: jnp.tensordot(a, b, axes=ax), _as_t(x), _as_t(y))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards

    def f(i):
        in_shard = (i // size) == shard_id
        return jnp.where(in_shard, i % size, ignore_value)

    return apply(f, _as_t(input))


def cast(x, dtype):
    return _as_t(x).astype(dtype)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    """Partial diagonal view: the diagonal of the (axis1, axis2) planes is
    appended as the last dimension (ref: paddle.diagonal semantics)."""
    return apply(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        _as_t(x))


def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis`: dimension axis becomes the window count
    and a trailing dimension of length `size` is appended (reference
    Tensor.unfold semantics — a strided view upstream; gather here, which XLA
    turns back into strided loads)."""
    x = _as_t(x)
    ax = axis % len(x.shape)
    n = x.shape[ax]
    if size > n:
        raise ValueError(f"unfold size {size} exceeds dim {n} at axis {axis}")
    starts = jnp.arange(0, n - size + 1, step)

    def f(a):
        idx = starts[:, None] + jnp.arange(size)[None, :]
        out = jnp.take(a, idx, axis=ax)  # [..., n_win, size, ...]
        return jnp.moveaxis(out, ax + 1, -1)

    return apply(f, x)


def as_strided(x, shape, stride, offset=0, name=None):
    """Arbitrary strided view over the (row-major) underlying buffer.
    The reference aliases memory; jax arrays are immutable so this gathers
    the same element pattern (grads scatter-add back, matching overlapping
    -window autograd semantics)."""
    x = _as_t(x)

    def f(a):
        idx = jnp.asarray(offset, jnp.int32)
        nd = len(shape)
        for i, (sh, st) in enumerate(zip(shape, stride)):
            ar = jnp.arange(sh, dtype=jnp.int32) * st
            idx = idx + ar.reshape((sh,) + (1,) * (nd - 1 - i))
        return jnp.take(a.reshape(-1), idx)

    return apply(f, x)


def fliplr(x, name=None):
    return apply(lambda a: jnp.fliplr(a), _as_t(x), _op_name="fliplr")


def flipud(x, name=None):
    return apply(lambda a: jnp.flipud(a), _as_t(x), _op_name="flipud")


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = _as_t(x)
    import numpy as np

    a_len = int(x.shape[axis])
    if isinstance(num_or_indices, int):
        # keep empty trailing chunks (reference/np semantics when
        # num > axis length)
        sections = np.array_split(np.arange(a_len), num_or_indices)
        bounds = [0]
        for s in sections:
            bounds.append(bounds[-1] + len(s))
    else:
        bounds = [0] + [int(i) for i in num_or_indices] + [a_len]
    outs = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        outs.append(apply(
            lambda a, lo=int(lo), hi=int(hi): lax.slice_in_dim(a, lo, hi, axis=axis),
            x, _op_name="tensor_split"))
    return outs


def hsplit(x, num_or_indices, name=None):
    x = _as_t(x)
    axis = 0 if len(x.shape) == 1 else 1
    return tensor_split(x, num_or_indices, axis=axis)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    ts = [_as_t(v) for v in x]
    axis = 0 if len(ts[0].shape) <= 1 else 1
    return concat(ts, axis=axis)


def vstack(x, name=None):
    ts = [_as_t(v) for v in x]
    if len(ts[0].shape) <= 1:
        ts = [reshape(t_, [1, -1]) for t_ in ts]
    return concat(ts, axis=0)


def column_stack(x, name=None):
    ts = [_as_t(v) for v in x]
    ts = [reshape(t_, [-1, 1]) if len(t_.shape) == 1 else t_ for t_ in ts]
    return concat(ts, axis=1)


def row_stack(x, name=None):
    return vstack(x)


def unflatten(x, axis, shape, name=None):
    x = _as_t(x)
    axis = axis % len(x.shape)
    new_shape = (list(x.shape[:axis]) + [int(s) for s in shape]
                 + list(x.shape[axis + 1:]))
    return reshape(x, new_shape)


def index_fill(x, index, axis, value, name=None):
    x = _as_t(x)
    idx = _as_t(index)

    def f(a, i):
        moved = jnp.moveaxis(a, axis, 0)
        filled = moved.at[i].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(filled, 0, axis)

    return apply(f, x, idx.detach(), _op_name="index_fill")


def broadcast_shape(x_shape, y_shape):
    import numpy as np

    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def tolist(x, name=None):
    import numpy as np

    return np.asarray(_as_t(x)._data).tolist()


def shape(x, name=None):
    """paddle.shape: the shape as a 1-D int32 Tensor (reference returns a
    tensor, not a list — code feeds it to reshape etc.)."""
    from ..core.tensor import Tensor

    return Tensor(jnp.asarray([int(s) for s in _as_t(x).shape], jnp.int32))


# reference-compatible aliases
cat = concat
take_along_dim = take_along_axis
