"""Statistics namespace (ref: python/paddle/tensor/stat.py (U)) — thin
re-exports; the implementations live in math/search."""

from .math import mean, std, var, nanmean, nansum
from .search import median, nanmedian, quantile
from ..core.op_call import apply
from .creation import _as_t

import jax.numpy as jnp


def numel(x, name=None):
    from .attribute import numel as _n

    return _n(x)
