"""Tensor creation ops (ref: python/paddle/tensor/creation.py (U))."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..core.dtype import to_jax_dtype, get_default_dtype
from ..core.op_call import apply


def _dt(dtype, default=None):
    if dtype is None:
        return default if default is not None else get_default_dtype()
    return to_jax_dtype(dtype)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = jnp.bool_
        elif isinstance(fill_value, int):
            dtype = jnp.int32
        else:
            dtype = get_default_dtype()
    else:
        dtype = to_jax_dtype(dtype)
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, dtype))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return apply(lambda a: jnp.zeros_like(a, dtype=_dt(dtype, a.dtype) if dtype else None), _as_t(x))


def ones_like(x, dtype=None, name=None):
    return apply(lambda a: jnp.ones_like(a, dtype=_dt(dtype, a.dtype) if dtype else None), _as_t(x))


def full_like(x, fill_value, dtype=None, name=None):
    return apply(lambda a: jnp.full_like(a, fill_value, dtype=_dt(dtype, a.dtype) if dtype else None), _as_t(x))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python scalars")
    if dtype is None:
        # int32 is the TPU-native integer width (x64 stays disabled)
        dtype = jnp.int32 if all(isinstance(v, (int, type(None))) for v in (start, end, step)) else get_default_dtype()
    else:
        dtype = to_jax_dtype(dtype)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=dtype))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def meshgrid(*args, **kwargs):
    args = [_as_t(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[a._data for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def tril(x, diagonal=0, name=None):
    return apply(lambda a: jnp.tril(a, k=diagonal), _as_t(x))


def triu(x, diagonal=0, name=None):
    return apply(lambda a: jnp.triu(a, k=diagonal), _as_t(x))


def diag(x, offset=0, padding_value=0, name=None):
    x = _as_t(x)
    if x.ndim == 1 and padding_value != 0:
        def f(a):
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            return base + jnp.diagflat(a - padding_value, k=offset) * 0 + (jnp.diagflat(a, k=offset) - jnp.diagflat(jnp.full_like(a, padding_value), k=offset))
        return apply(f, x)
    return apply(lambda a: jnp.diag(a, k=offset), x)


def diagflat(x, offset=0, name=None):
    return apply(lambda a: jnp.diagflat(a, k=offset), _as_t(x))


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        out = jnp.zeros(a.shape + (a.shape[-1] + abs(offset),), a.dtype)
        idx = jnp.arange(a.shape[-1])
        out = out.at[..., idx, idx + max(offset, 0)].set(a) if offset >= 0 else out.at[..., idx - offset, idx].set(a)
        # embed into (dim1, dim2): default trailing two dims
        return out
    return apply(f, _as_t(x))


def assign(x, output=None):
    x = _as_t(x)
    out = apply(lambda a: a + 0, x)
    if output is not None:
        output.set_value(out._data)
        return output
    return out


def clone(x, name=None):
    return _as_t(x).clone()


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jnp.eye(num_classes, dtype=get_default_dtype())[a.astype(jnp.int32)], _as_t(x))


def complex(real, imag, name=None):
    return apply(lambda r, i: r + 1j * i, _as_t(real), _as_t(imag))


def polar(abs_, angle, name=None):
    return apply(lambda r, t: r * jnp.exp(1j * t), _as_t(abs_), _as_t(angle))


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def _as_t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    """[2, n] indices of the lower triangle (reference layout)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    from ..core.dtype import to_jax_dtype

    if col is None:
        col = row
    r, c = jnp.tril_indices(int(row), k=offset, m=int(col))
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    from ..core.dtype import to_jax_dtype

    if col is None:
        col = row
    r, c = jnp.triu_indices(int(row), k=offset, m=int(col))
    return Tensor(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))
