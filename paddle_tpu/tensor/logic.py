"""Comparison / logical / bitwise ops (ref: python/paddle/tensor/logic.py (U))."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.op_call import apply
from .creation import _as_t


def _cmp(fn, x, y):
    x = _as_t(x)
    if isinstance(y, Tensor):
        return apply(fn, x.detach(), y.detach())
    return apply(lambda a: fn(a, y), x.detach())


def equal(x, y, name=None):
    return _cmp(jnp.equal, x, y)


def not_equal(x, y, name=None):
    return _cmp(jnp.not_equal, x, y)


def greater_than(x, y, name=None):
    return _cmp(jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return _cmp(jnp.greater_equal, x, y)


def less_than(x, y, name=None):
    return _cmp(jnp.less, x, y)


def less_equal(x, y, name=None):
    return _cmp(jnp.less_equal, x, y)


def logical_and(x, y, out=None, name=None):
    return _cmp(jnp.logical_and, x, y)


def logical_or(x, y, out=None, name=None):
    return _cmp(jnp.logical_or, x, y)


def logical_xor(x, y, out=None, name=None):
    return _cmp(jnp.logical_xor, x, y)


def logical_not(x, out=None, name=None):
    return apply(jnp.logical_not, _as_t(x).detach())


def bitwise_and(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return apply(jnp.bitwise_not, _as_t(x).detach())


def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return _cmp(jnp.left_shift, x, y)


def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    return _cmp(jnp.right_shift, x, y)


def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), _as_t(x).detach())


def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), _as_t(x).detach())


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_as_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def in_dynamic_mode():
    from ..static.graph import in_static_mode

    return not in_static_mode()
