"""Linear algebra (ref: python/paddle/tensor/linalg.py (U)) over jnp.linalg.

Note: on TPU most decompositions (svd/qr/eigh) lower to XLA's host-offloaded
or polynomial implementations; fine for the API surface, not a perf path.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.op_call import apply
from .creation import _as_t
from .math import matmul, dot, cross  # re-exported by paddle.linalg


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if axis is None and p is None:
            return jnp.linalg.norm(a.reshape(-1))
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        o = p if p is not None else (2 if isinstance(ax, int) else "fro")
        if o == "fro" and isinstance(ax, int):
            o = 2
        return jnp.linalg.norm(a, ord=o, axis=ax, keepdims=keepdim)

    return apply(f, _as_t(x), _op_name="norm")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply(lambda a: jnp.linalg.vector_norm(a, ord=p, axis=ax, keepdims=keepdim), _as_t(x))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_norm(a, ord=p, keepdims=keepdim), _as_t(x))


def cond(x, p=None, name=None):
    return apply(lambda a: jnp.linalg.cond(a, p=p), _as_t(x))


def det(x, name=None):
    return apply(jnp.linalg.det, _as_t(x))


def slogdet(x, name=None):
    def f(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])

    return apply(f, _as_t(x))


def inv(x, name=None):
    return apply(jnp.linalg.inv, _as_t(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), _as_t(x))


def svd(x, full_matrices=False, name=None):
    out = apply(lambda a: jnp.linalg.svd(a, full_matrices=full_matrices), _as_t(x))
    return out[0], out[1], out[2]


def svdvals(x, name=None):
    return apply(lambda a: jnp.linalg.svd(a, compute_uv=False), _as_t(x))


def qr(x, mode="reduced", name=None):
    out = apply(lambda a: jnp.linalg.qr(a, mode=mode), _as_t(x))
    return (out[0], out[1]) if mode != "r" else out


def eig(x, name=None):
    import numpy as np

    # jnp.linalg.eig is CPU-only in jax; route via numpy eagerly (API parity)
    w, v = np.linalg.eig(np.asarray(_as_t(x)._data))
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    out = apply(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), _as_t(x))
    return out[0], out[1]


def eigvals(x, name=None):
    import numpy as np

    return Tensor(np.linalg.eigvals(np.asarray(_as_t(x)._data)))


def eigvalsh(x, UPLO="L", name=None):
    return apply(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), _as_t(x))


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2).conj() if upper else l

    return apply(f, _as_t(x))


def cholesky_solve(x, y, upper=False, name=None):
    from jax.scipy.linalg import cho_solve

    return apply(lambda b, c: cho_solve((c, not upper), b), _as_t(x), _as_t(y))


def solve(x, y, name=None):
    return apply(jnp.linalg.solve, _as_t(x), _as_t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    from jax.scipy.linalg import solve_triangular

    return apply(
        lambda a, b: solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular),
        _as_t(x), _as_t(y),
    )


def lstsq(x, y, rcond=None, driver=None, name=None):
    out = apply(lambda a, b: jnp.linalg.lstsq(a, b, rcond=rcond), _as_t(x), _as_t(y))
    return tuple(out)


def lu(x, pivot=True, get_infos=False, name=None):
    from jax.scipy.linalg import lu_factor

    out = apply(lambda a: lu_factor(a), _as_t(x))
    lu_mat, piv = out[0], out[1]
    if get_infos:
        from .creation import zeros

        return lu_mat, piv, zeros([1], dtype="int32")
    return lu_mat, piv


def matrix_power(x, n, name=None):
    return apply(lambda a: jnp.linalg.matrix_power(a, n), _as_t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply(lambda a: jnp.linalg.matrix_rank(a, rtol=tol), _as_t(x))


def multi_dot(x, name=None):
    ts = [_as_t(t) for t in x]
    return apply(lambda *xs: jnp.linalg.multi_dot(xs), *ts)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = _as_t(x)
    qv = q if q is not None else min(6, x.shape[-2], x.shape[-1])

    def f(a):
        m = a - a.mean(axis=-2, keepdims=True) if center else a
        u, s, vt = jnp.linalg.svd(m, full_matrices=False)
        return u[..., :qv], s[..., :qv], jnp.swapaxes(vt, -1, -2)[..., :qv]

    out = apply(f, x)
    return out[0], out[1], out[2]


def corrcoef(x, rowvar=True, name=None):
    return apply(lambda a: jnp.corrcoef(a, rowvar=rowvar), _as_t(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), _as_t(x))


def householder_product(x, tau, name=None):
    def f(a, t):
        return _householder_q(a, t)[:, :a.shape[-1]]

    return apply(f, _as_t(x), _as_t(tau))


def _p_reduce(d, p):
    """Reduce a difference tensor over its last axis to the p-distance."""
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype), axis=-1)
    if math.isinf(p):
        return jnp.max(jnp.abs(d), axis=-1)
    if p == 1:
        return jnp.sum(jnp.abs(d), axis=-1)
    if p == 2:
        return jnp.sqrt(jnp.maximum(jnp.sum(d * d, axis=-1), 1e-30))
    return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Batched pairwise p-distances: [..., P, M] x [..., R, M] -> [..., P, R].
    Difference-based (accurate); compute_mode's mm shortcut is an upstream
    CUDA-perf knob — on TPU XLA fuses the broadcast subtract into the
    reduction, so one formula serves."""
    return apply(
        lambda a, b: _p_reduce(a[..., :, None, :] - b[..., None, :, :], p),
        _as_t(x), _as_t(y))


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of an [N, M] matrix: the strict upper
    triangle of cdist(x, x), row-major, shape [N*(N-1)/2]."""
    x = _as_t(x)
    iu, ju = np.triu_indices(x.shape[0], k=1)
    return apply(lambda a: _p_reduce(a[iu] - a[ju], p), x)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack lu() output into P, L, U (reference lu_unpack; supports the
    batched factors this repo's lu() produces)."""
    import jax

    lu_t = _as_t(lu_data)
    piv = _as_t(lu_pivots)

    def single(a, p):
        m, n = a.shape[-2], a.shape[-1]
        k = min(m, n)
        L = jnp.tril(a[:, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[:k, :])
        # pivots (0-based row swaps, jax lu_factor convention) -> permutation
        perm = jnp.arange(m)

        def body(i, perm):
            j = p[i]
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj)
            return perm.at[j].set(pi)

        from jax import lax

        perm = lax.fori_loop(0, p.shape[-1], body, perm)
        P = jnp.eye(m, dtype=a.dtype)[perm].T
        return P, L, U

    def f(a, p):
        fn = single
        for _ in range(a.ndim - 2):
            fn = jax.vmap(fn)
        return fn(a, p)

    P, L, U = apply(f, lu_t, piv.detach())
    return (P if unpack_pivots else None,
            L if unpack_ludata else None,
            U if unpack_ludata else None)


def matrix_exp(x, name=None):
    from jax.scipy.linalg import expm

    return apply(lambda a: expm(a), _as_t(x))


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by Q (from the Householder factors x, tau):
    Q @ y / Q^T @ y / y @ Q / y @ Q^T. Batched factors supported."""
    import jax

    def single(a, t, b):
        q = _householder_q(a, t)
        qm = q.T if transpose else q
        return qm @ b if left else b @ qm

    def f(a, t, b):
        fn = single
        for _ in range(a.ndim - 2):
            fn = jax.vmap(fn)
        return fn(a, t, b)

    return apply(f, _as_t(x), _as_t(tau), _as_t(y))


def _householder_q(a, tau):
    m, k = a.shape[-2], tau.shape[-1]
    q = jnp.eye(m, dtype=a.dtype)
    for i in range(k):
        v = jnp.zeros((m,), a.dtype).at[i].set(1.0)
        v = v.at[i + 1:].set(a[i + 1:, i])
        h = jnp.eye(m, dtype=a.dtype) - tau[i] * jnp.outer(v, v)
        q = q @ h
    return q


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized truncated SVD (reference svd_lowrank): subspace iteration
    with a fixed-seed test matrix (deterministic, jit-friendly); M, when
    given, is subtracted first (the reference's PCA-centering contract).
    Batched input supported."""
    x_t = _as_t(x)
    args = [x_t] + ([_as_t(M)] if M is not None else [])

    def f(a, *m):
        import jax

        if m:
            a = a - m[0]
        mT = lambda t: jnp.swapaxes(t, -1, -2)  # batch-safe transpose
        n = a.shape[-1]
        k = min(q, a.shape[-2], n)
        omega = jax.random.normal(jax.random.key(0), (n, k), a.dtype)
        # subspace iteration with QR re-orthonormalization each step —
        # plain power iteration collapses onto the top singular vector
        # in f32 and loses the rest of the subspace
        qmat, _ = jnp.linalg.qr(a @ omega)
        for _ in range(niter):
            z, _ = jnp.linalg.qr(mT(a) @ qmat)
            qmat, _ = jnp.linalg.qr(a @ z)
        b = mT(qmat) @ a
        u_b, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u_b, s, mT(vh)

    out = apply(f, *args)
    return tuple(out)
