"""Search/sort ops (ref: python/paddle/tensor/search.py (U))."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.op_call import apply
from .creation import _as_t


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        if axis is None:
            r = jnp.argmax(a.reshape(-1))
            return r.reshape((1,) * a.ndim) if keepdim else r
        r = jnp.argmax(a, axis=axis)
        return jnp.expand_dims(r, axis) if keepdim else r

    return apply(f, _as_t(x).detach())


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def f(a):
        if axis is None:
            r = jnp.argmin(a.reshape(-1))
            return r.reshape((1,) * a.ndim) if keepdim else r
        r = jnp.argmin(a, axis=axis)
        return jnp.expand_dims(r, axis) if keepdim else r

    return apply(f, _as_t(x).detach())


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=stable or True)
        return jnp.flip(idx, axis=axis) if descending else idx

    return apply(f, _as_t(x).detach())


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return apply(f, _as_t(x), _op_name="sort")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k._data)
    x = _as_t(x)

    def f(a):
        ax = axis % a.ndim
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = lax.top_k(am, k)
        else:
            v, i = lax.top_k(-am, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax)

    out = apply(f, x, _op_name="topk")
    return out[0], out[1]


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        ix = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ix = jnp.expand_dims(ix, axis)
        return v, ix

    out = apply(f, _as_t(x))
    return out[0], out[1]


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    from scipy import stats as _stats  # available via numpy ecosystem

    a = np.asarray(_as_t(x)._data)
    m = _stats.mode(a, axis=axis, keepdims=keepdim)
    return Tensor(m.mode), Tensor(m.count)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def f(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side)
        import jax

        return jax.vmap(lambda s1, v1: jnp.searchsorted(s1, v1, side=side))(
            s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape)

    return apply(f, _as_t(sorted_sequence).detach(), _as_t(values).detach())


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply(lambda a: jnp.median(a, axis=axis, keepdims=keepdim), _as_t(x), _op_name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply(lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), _as_t(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._data if isinstance(q, Tensor) else q
    return apply(lambda a: jnp.quantile(a, jnp.asarray(qv), axis=axis, keepdims=keepdim, method=interpolation), _as_t(x))


def histogram(input, bins=100, min=0, max=0, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
        return h

    return apply(f, _as_t(input).detach())


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    import numpy as np

    h, edges = np.histogramdd(np.asarray(_as_t(x)._data), bins=bins, range=ranges, density=density,
                              weights=None if weights is None else np.asarray(_as_t(weights)._data))
    return Tensor(h), [Tensor(e) for e in edges]


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = q._data if isinstance(q, Tensor) else q
    return apply(
        lambda a: jnp.nanquantile(a, jnp.asarray(qv), axis=axis,
                                  keepdims=keepdim, method=interpolation),
        _as_t(x))
