"""paddle.tensor-equivalent namespace: re-exports every op and monkey-patches
them onto Tensor as methods + operators — mirroring how the reference attaches
its ~700 tensor methods to the pybind Tensor (upstream python/paddle/tensor/__init__.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter, to_tensor

from .creation import (
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, meshgrid, tril, triu, diag, diagflat,
    diag_embed, assign, clone, one_hot, complex, polar, tril_indices,
    triu_indices,
)
from .math import (
    add, subtract, multiply, divide, floor_divide, remainder, mod, floor_mod,
    pow, maximum, minimum, fmax, fmin, atan2, hypot, gcd, lcm, heaviside,
    nextafter, copysign, ldexp, logaddexp, sqrt, rsqrt, square, exp, expm1,
    log, log2, log10, log1p, abs, neg, negative, sign, sgn, sin, cos, tan,
    asin, acos, atan, sinh, cosh, tanh, asinh, acosh, atanh, floor, ceil,
    round, trunc, frac, reciprocal, sigmoid, logsigmoid, erf, erfinv, lgamma,
    digamma, i0, angle, conj, real, imag, deg2rad, rad2deg, isnan, isinf,
    isfinite, nan_to_num, clip, scale, stanh, lerp, sum, nansum, mean,
    nanmean, max, min, amax, amin, prod, std, var, logsumexp, cumsum,
    cumprod, cummax, cummin, count_nonzero, diff, trace, add_n, matmul, mm,
    bmm, dot, inner, outer, kron, mv, addmm, cross, allclose, isclose,
    equal_all, increment, multiplex, bincount, trapezoid,
    cumulative_trapezoid, vander, logcumsumexp, frexp, renorm, i0e, i1, i1e,
    polygamma, logit, signbit, positive, dist, inverse, combinations,
    gammaln, gammainc, gammaincc,
)
from .manipulation import (
    reshape, reshape_, transpose, t, moveaxis, swapaxes, flatten, squeeze,
    unsqueeze, concat, stack, split, chunk, unbind, unstack, tile, expand,
    expand_as, broadcast_to, broadcast_tensors, flip, rot90, roll, gather,
    gather_nd, scatter, scatter_, scatter_nd_add, scatter_nd, index_select,
    index_sample, index_add, index_put, masked_select, masked_fill,
    take_along_axis, put_along_axis, take, slice, strided_slice,
    repeat_interleave, unique, unique_consecutive, nonzero, where,
    as_complex, as_real, view, view_as, atleast_1d, atleast_2d, atleast_3d,
    tensordot, shard_index, cast, diagonal, unfold, as_strided, fliplr,
    flipud, tensor_split, hsplit, vsplit, dsplit, hstack, vstack,
    column_stack, row_stack, unflatten, index_fill, broadcast_shape, tolist,
    shape, cat, take_along_dim,
)
from .logic import (
    equal, not_equal, greater_than, greater_equal, less_than, less_equal,
    logical_and, logical_or, logical_xor, logical_not, bitwise_and,
    bitwise_or, bitwise_xor, bitwise_not, bitwise_left_shift,
    bitwise_right_shift, all, any, is_empty, is_tensor, in_dynamic_mode,
)
from .search import (
    argmax, argmin, argsort, sort, topk, kthvalue, mode, searchsorted,
    bucketize, median, nanmedian, quantile, nanquantile, histogram,
    histogramdd,
)
# root-level linalg aliases, matching the reference's paddle.<fn> re-exports
from .linalg import (
    norm, pinv, slogdet, matrix_power, matrix_rank, multi_dot, cov, corrcoef,
    det, inv, cdist, pdist,
)
from .random import (
    rand, randn, standard_normal, normal, uniform, randint, randint_like,
    randperm, multinomial, bernoulli, poisson, rand_like, randn_like,
    uniform_, bernoulli_, exponential_, normal_, gumbel_softmax,
)
from .einsum import einsum
from .attribute import shape as shape_fn, rank, numel, is_complex, is_floating_point, is_integer
from . import creation, math, manipulation, logic, search, linalg, random, stat


def _patch():
    import builtins as _bi
    from . import math as _m, manipulation as _mp, logic as _lg, search as _s, creation as _c, linalg as _la, random as _r

    methods = {}
    for mod in (_m, _mp, _lg, _s, _la):
        for name in dir(mod):
            if name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if callable(fn) and not isinstance(fn, type):
                methods.setdefault(name, fn)
    # in-place random mutators are legitimate Tensor methods
    for name in ("uniform_", "normal_", "bernoulli_", "exponential_"):
        methods[name] = getattr(_r, name)

    skip = {"shape", "slice"}  # don't clobber property / builtin-ish
    for name, fn in methods.items():
        if name in skip or hasattr(Tensor, name):
            continue
        setattr(Tensor, name, fn)

    # method aliases paddle exposes
    Tensor.numpy  # exists
    Tensor.mod = _m.remainder
    Tensor.pow = _m.pow
    Tensor.abs = _m.abs
    Tensor.any = _lg.any
    Tensor.all = _lg.all
    Tensor.norm = _la.norm
    Tensor.flatten = _mp.flatten
    Tensor.unflatten = lambda self, axis, shape: _mp.reshape(
        self, self.shape[:axis] + list(shape) + self.shape[axis + 1:]
    )

    # operators
    Tensor.__add__ = lambda self, o: _m.add(self, o)
    Tensor.__radd__ = lambda self, o: _m.add(self, o)
    Tensor.__sub__ = lambda self, o: _m.subtract(self, o)
    Tensor.__rsub__ = lambda self, o: _m._rbinary(jnp.subtract, self, o if not isinstance(o, Tensor) else o._data, "rsub")
    Tensor.__mul__ = lambda self, o: _m.multiply(self, o)
    Tensor.__rmul__ = lambda self, o: _m.multiply(self, o)
    Tensor.__truediv__ = lambda self, o: _m.divide(self, o)
    Tensor.__rtruediv__ = lambda self, o: _m._rbinary(jnp.true_divide, self, o if not isinstance(o, Tensor) else o._data, "rdiv")
    Tensor.__floordiv__ = lambda self, o: _m.floor_divide(self, o)
    Tensor.__mod__ = lambda self, o: _m.remainder(self, o)
    Tensor.__pow__ = lambda self, o: _m.pow(self, o)
    Tensor.__rpow__ = lambda self, o: _m._rbinary(jnp.power, self, o if not isinstance(o, Tensor) else o._data, "rpow")
    Tensor.__matmul__ = lambda self, o: _m.matmul(self, o)
    Tensor.__rmatmul__ = lambda self, o: _m.matmul(o if isinstance(o, Tensor) else to_tensor(o), self)
    Tensor.__neg__ = lambda self: _m.neg(self)
    Tensor.__abs__ = lambda self: _m.abs(self)
    Tensor.__invert__ = lambda self: _lg.logical_not(self) if self.dtype == jnp.bool_ else _lg.bitwise_not(self)
    Tensor.__eq__ = lambda self, o: _lg.equal(self, o)
    Tensor.__ne__ = lambda self, o: _lg.not_equal(self, o)
    Tensor.__lt__ = lambda self, o: _lg.less_than(self, o)
    Tensor.__le__ = lambda self, o: _lg.less_equal(self, o)
    Tensor.__gt__ = lambda self, o: _lg.greater_than(self, o)
    Tensor.__ge__ = lambda self, o: _lg.greater_equal(self, o)
    Tensor.__and__ = lambda self, o: _lg.logical_and(self, o) if self.dtype == jnp.bool_ else _lg.bitwise_and(self, o)
    Tensor.__or__ = lambda self, o: _lg.logical_or(self, o) if self.dtype == jnp.bool_ else _lg.bitwise_or(self, o)
    Tensor.__xor__ = lambda self, o: _lg.logical_xor(self, o) if self.dtype == jnp.bool_ else _lg.bitwise_xor(self, o)


_patch()
