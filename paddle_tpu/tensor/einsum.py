"""einsum (ref: python/paddle/tensor/einsum.py (U)) — delegates to jnp.einsum,
which XLA maps straight onto MXU contractions."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_call import apply
from .creation import _as_t


def einsum(equation, *operands):
    ts = [_as_t(o) for o in operands]
    return apply(lambda *xs: jnp.einsum(equation, *xs), *ts, _op_name="einsum")
