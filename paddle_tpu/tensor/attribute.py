"""Tensor attribute queries (ref: python/paddle/tensor/attribute.py (U))."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from .creation import _as_t


def shape(x):
    from .creation import to_tensor as _tt
    return Tensor(jnp.asarray(_as_t(x).shape, dtype=jnp.int64)) if False else Tensor(jnp.asarray(_as_t(x).shape))


def rank(x):
    return Tensor(jnp.asarray(_as_t(x).ndim))


def numel(x, name=None):
    return Tensor(jnp.asarray(_as_t(x).size))


def is_complex(x):
    return jnp.issubdtype(_as_t(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_as_t(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_as_t(x).dtype, jnp.integer)
