"""Math ops (ref: python/paddle/tensor/math.py (U)) over jnp — XLA fuses the
elementwise chains into surrounding matmuls on TPU, so these stay unfused here."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, to_tensor
from ..core.op_call import apply
from .creation import _as_t


def _b(x):
    """Coerce binary operand: Tensor passes through, scalars stay raw (jnp broadcasts)."""
    return x if isinstance(x, Tensor) else x


def _binary(fn, x, y, name):
    x = _as_t(x) if not isinstance(x, Tensor) else x
    if isinstance(y, Tensor):
        return apply(fn, x, y, _op_name=name)
    return apply(lambda a: fn(a, y), x, _op_name=name)


def _rbinary(fn, x, y, name):
    # y op x with x Tensor
    return apply(lambda a: fn(y, a), x, _op_name=name)


def _unary(fn, x, name=None):
    return apply(fn, _as_t(x), _op_name=name or fn.__name__)


# ----- elementwise binary -----
def add(x, y, name=None):
    return _binary(jnp.add, x, y, "add")


def subtract(x, y, name=None):
    return _binary(jnp.subtract, x, y, "subtract")


def multiply(x, y, name=None):
    return _binary(jnp.multiply, x, y, "multiply")


def divide(x, y, name=None):
    return _binary(jnp.true_divide, x, y, "divide")


def floor_divide(x, y, name=None):
    return _binary(jnp.floor_divide, x, y, "floor_divide")


def remainder(x, y, name=None):
    return _binary(jnp.remainder, x, y, "remainder")


mod = remainder
floor_mod = remainder


def pow(x, y, name=None):
    return _binary(jnp.power, x, y, "pow")


def maximum(x, y, name=None):
    return _binary(jnp.maximum, x, y, "maximum")


def minimum(x, y, name=None):
    return _binary(jnp.minimum, x, y, "minimum")


def fmax(x, y, name=None):
    return _binary(jnp.fmax, x, y, "fmax")


def fmin(x, y, name=None):
    return _binary(jnp.fmin, x, y, "fmin")


def atan2(x, y, name=None):
    return _binary(jnp.arctan2, x, y, "atan2")


def hypot(x, y, name=None):
    return _binary(jnp.hypot, x, y, "hypot")


def gcd(x, y, name=None):
    return _binary(jnp.gcd, x, y, "gcd")


def lcm(x, y, name=None):
    return _binary(jnp.lcm, x, y, "lcm")


def heaviside(x, y, name=None):
    return _binary(jnp.heaviside, x, y, "heaviside")


def nextafter(x, y, name=None):
    return _binary(jnp.nextafter, x, y, "nextafter")


def copysign(x, y, name=None):
    return _binary(jnp.copysign, x, y, "copysign")


def ldexp(x, y, name=None):
    return _binary(lambda a, b: a * (2.0 ** b), x, y, "ldexp")


def logaddexp(x, y, name=None):
    return _binary(jnp.logaddexp, x, y, "logaddexp")


# ----- elementwise unary -----
def sqrt(x, name=None):
    return _unary(jnp.sqrt, x)


def rsqrt(x, name=None):
    return _unary(lax.rsqrt, x, "rsqrt")


def square(x, name=None):
    return _unary(jnp.square, x)


def exp(x, name=None):
    return _unary(jnp.exp, x)


def expm1(x, name=None):
    return _unary(jnp.expm1, x)


def log(x, name=None):
    return _unary(jnp.log, x)


def log2(x, name=None):
    return _unary(jnp.log2, x)


def log10(x, name=None):
    return _unary(jnp.log10, x)


def log1p(x, name=None):
    return _unary(jnp.log1p, x)


def abs(x, name=None):
    return _unary(jnp.abs, x)


def neg(x, name=None):
    return _unary(jnp.negative, x, "neg")


negative = neg


def sign(x, name=None):
    return _unary(jnp.sign, x)


def sgn(x, name=None):
    return _unary(jnp.sign, x)


def sin(x, name=None):
    return _unary(jnp.sin, x)


def cos(x, name=None):
    return _unary(jnp.cos, x)


def tan(x, name=None):
    return _unary(jnp.tan, x)


def asin(x, name=None):
    return _unary(jnp.arcsin, x, "asin")


def acos(x, name=None):
    return _unary(jnp.arccos, x, "acos")


def atan(x, name=None):
    return _unary(jnp.arctan, x, "atan")


def sinh(x, name=None):
    return _unary(jnp.sinh, x)


def cosh(x, name=None):
    return _unary(jnp.cosh, x)


def tanh(x, name=None):
    return _unary(jnp.tanh, x)


def asinh(x, name=None):
    return _unary(jnp.arcsinh, x, "asinh")


def acosh(x, name=None):
    return _unary(jnp.arccosh, x, "acosh")


def atanh(x, name=None):
    return _unary(jnp.arctanh, x, "atanh")


def floor(x, name=None):
    return _unary(jnp.floor, x)


def ceil(x, name=None):
    return _unary(jnp.ceil, x)


def round(x, name=None):
    return _unary(jnp.round, x)


def trunc(x, name=None):
    return _unary(jnp.trunc, x)


def frac(x, name=None):
    return _unary(lambda a: a - jnp.trunc(a), x, "frac")


def reciprocal(x, name=None):
    return _unary(jnp.reciprocal, x)


def sigmoid(x, name=None):
    return _unary(jax.nn.sigmoid, x, "sigmoid")


def logsigmoid(x, name=None):
    return _unary(jax.nn.log_sigmoid, x, "logsigmoid")


def erf(x, name=None):
    return _unary(jax.scipy.special.erf, x, "erf")


def erfinv(x, name=None):
    return _unary(jax.scipy.special.erfinv, x, "erfinv")


def lgamma(x, name=None):
    return _unary(jax.scipy.special.gammaln, x, "lgamma")


def digamma(x, name=None):
    return _unary(jax.scipy.special.digamma, x, "digamma")


def gammaln(x, name=None):
    return _unary(jax.scipy.special.gammaln, x, "gammaln")


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (ref: paddle.gammainc)."""
    return _binary(jax.scipy.special.gammainc, x, y, "gammainc")


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y) (ref: paddle.gammaincc)."""
    return _binary(jax.scipy.special.gammaincc, x, y, "gammaincc")


def i0(x, name=None):
    return _unary(jnp.i0, x)


def angle(x, name=None):
    return _unary(jnp.angle, x)


def conj(x, name=None):
    return _unary(jnp.conj, x)


def real(x, name=None):
    return _unary(jnp.real, x)


def imag(x, name=None):
    return _unary(jnp.imag, x)


def deg2rad(x, name=None):
    return _unary(jnp.deg2rad, x)


def rad2deg(x, name=None):
    return _unary(jnp.rad2deg, x)


def isnan(x, name=None):
    return _unary(jnp.isnan, x)


def isinf(x, name=None):
    return _unary(jnp.isinf, x)


def isfinite(x, name=None):
    return _unary(jnp.isfinite, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _unary(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x, "nan_to_num")


def clip(x, min=None, max=None, name=None):
    lo = min._data if isinstance(min, Tensor) else min
    hi = max._data if isinstance(max, Tensor) else max
    return _unary(lambda a: jnp.clip(a, lo, hi), x, "clip")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale._data if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        out = _unary(lambda a: a * s + bias, x, "scale")
    else:
        out = _unary(lambda a: (a + bias) * s, x, "scale")
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary(lambda a: scale_b * jnp.tanh(scale_a * a), x, "stanh")


def lerp(x, y, weight, name=None):
    w = weight._data if isinstance(weight, Tensor) else weight
    return apply(lambda a, b: a + w * (b - a), _as_t(x), _as_t(y), _op_name="lerp")


# ----- reductions -----
def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..core.dtype import to_jax_dtype

    jd = to_jax_dtype(dtype) if dtype else None
    return _unary(lambda a: jnp.sum(a, axis=_axis(axis), dtype=jd, keepdims=keepdim), x, "sum")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _unary(lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim), x, "nansum")


def mean(x, axis=None, keepdim=False, name=None):
    return _unary(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x, "mean")


def nanmean(x, axis=None, keepdim=False, name=None):
    return _unary(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x, "nanmean")


def max(x, axis=None, keepdim=False, name=None):
    return _unary(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x, "max")


def min(x, axis=None, keepdim=False, name=None):
    return _unary(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x, "min")


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _unary(lambda a: jnp.prod(a, axis=_axis(axis), keepdims=keepdim), x, "prod")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _unary(lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x, "std")


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _unary(lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x, "var")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _unary(lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x, "logsumexp")


def cumsum(x, axis=None, dtype=None, name=None):
    return _unary(lambda a: jnp.cumsum(a.reshape(-1) if axis is None else a, axis=None if axis is None else _axis(axis)), x, "cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    return _unary(lambda a: jnp.cumprod(a.reshape(-1) if dim is None else a, axis=None if dim is None else _axis(dim)), x, "cumprod")


def _cum_extreme(x, axis, op):
    ax = 0 if axis is None else _axis(axis)
    x2 = x if axis is not None else _unary(lambda a: a.reshape(-1), x)

    def f(a):
        n = a.shape[ax]
        shape = [1] * a.ndim
        shape[ax] = n
        idx = jnp.broadcast_to(jnp.arange(n).reshape(shape), a.shape)

        def combine(c1, c2):
            v1, i1 = c1
            v2, i2 = c2
            take2 = op(v2, v1)
            return jnp.where(take2, v2, v1), jnp.where(take2, i2, i1)

        return lax.associative_scan(combine, (a, idx), axis=ax)

    out = apply(f, x2)
    return out[0], out[1]


def cummax(x, axis=None, dtype=None, name=None):
    return _cum_extreme(x, axis, lambda a, b: a >= b)


def cummin(x, axis=None, dtype=None, name=None):
    return _cum_extreme(x, axis, lambda a, b: a <= b)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _unary(lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim), x, "count_nonzero")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    p = prepend._data if isinstance(prepend, Tensor) else prepend
    ap = append._data if isinstance(append, Tensor) else append
    return _unary(lambda a: jnp.diff(a, n=n, axis=axis, prepend=p, append=ap), x, "diff")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _unary(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x, "trace")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    ts = [_as_t(t) for t in inputs]
    return apply(lambda *xs: jnp.sum(jnp.stack(xs), axis=0) if len(xs) > 1 else xs[0], *ts, _op_name="add_n")


# ----- matmul family -----
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply(f, _as_t(x), _as_t(y), _op_name="matmul")


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return apply(lambda a, b: jnp.sum(a * b, axis=-1), _as_t(x), _as_t(y), _op_name="dot")


def inner(x, y, name=None):
    return apply(jnp.inner, _as_t(x), _as_t(y), _op_name="inner")


def outer(x, y, name=None):
    return apply(jnp.outer, _as_t(x), _as_t(y), _op_name="outer")


def kron(x, y, name=None):
    return apply(jnp.kron, _as_t(x), _as_t(y), _op_name="kron")


def mv(x, vec, name=None):
    return apply(jnp.matmul, _as_t(x), _as_t(vec), _op_name="mv")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply(lambda i, a, b: beta * i + alpha * (a @ b), _as_t(input), _as_t(x), _as_t(y), _op_name="addmm")


def cross(x, y, axis=None, name=None):
    ax = axis if axis is not None else -1
    return apply(lambda a, b: jnp.cross(a, b, axis=ax), _as_t(x), _as_t(y), _op_name="cross")


# ----- comparisons that return values -----
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), _as_t(x), _as_t(y))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), _as_t(x), _as_t(y))


def equal_all(x, y, name=None):
    return apply(lambda a, b: jnp.array_equal(a, b), _as_t(x), _as_t(y))


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


def multiplex(inputs, index, name=None):
    """Row r of the output comes from inputs[index[r]][r] (paddle semantics)."""
    ts = [_as_t(t) for t in inputs]
    idx = _as_t(index).detach()

    def f(i, *xs):
        stacked = jnp.stack(xs)  # [n_inputs, rows, ...]
        rows = jnp.arange(stacked.shape[1])
        sel = i.reshape(-1).astype(jnp.int32)
        return stacked[sel, rows]

    return apply(f, idx, *ts, _op_name="multiplex")


def bincount(x, weights=None, minlength=0, name=None):
    """ref paddle.bincount. Note: under jit the output length must be
    static, so the count is taken from the concrete input."""
    import numpy as np

    xt = _as_t(x)
    x_np = np.asarray(xt._data)
    if x_np.size and x_np.min() < 0:
        raise ValueError("bincount: input must be non-negative")
    n = int(x_np.max()) + 1 if x_np.size else 0
    if int(minlength) > n:
        n = int(minlength)
    args = [xt] + ([_as_t(weights)] if weights is not None else [])

    def f(a, *w):
        return jnp.bincount(a.astype(jnp.int32), w[0] if w else None, length=n)

    return apply(f, *args, _op_name="bincount")


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """ref paddle.trapezoid (trapezoidal rule integration)."""
    yt = _as_t(y)
    if x is not None:
        xt = _as_t(x)
        return apply(lambda a, b: jnp.trapezoid(a, b, axis=axis), yt, xt,
                     _op_name="trapezoid")
    d = 1.0 if dx is None else dx
    return apply(lambda a: jnp.trapezoid(a, dx=d, axis=axis), yt,
                 _op_name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """ref paddle.cumulative_trapezoid."""
    yt = _as_t(y)

    def f(a, *b):
        a = jnp.moveaxis(a, axis, -1)
        mids = (a[..., 1:] + a[..., :-1]) / 2.0
        if b:
            xv = jnp.moveaxis(b[0], axis, -1)
            steps = xv[..., 1:] - xv[..., :-1]
        else:
            steps = 1.0 if dx is None else dx
        out = jnp.cumsum(mids * steps, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    args = [yt] + ([_as_t(x)] if x is not None else [])
    return apply(f, *args, _op_name="cumulative_trapezoid")


def vander(x, n=None, increasing=False, name=None):
    """ref paddle.vander (Vandermonde matrix)."""
    xt = _as_t(x)
    cols = n if n is not None else xt.shape[0]
    return apply(lambda a: jnp.vander(a, cols, increasing=increasing), xt,
                 _op_name="vander")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """Numerically stable running logsumexp via an associative logaddexp scan
    (parallel prefix on TPU — no serial loop)."""
    def f(a):
        if dtype is not None:
            a = a.astype(dtype)
        if axis is None:
            return lax.associative_scan(jnp.logaddexp, a.reshape(-1))
        return lax.associative_scan(jnp.logaddexp, a, axis=axis)

    return apply(f, _as_t(x))


def frexp(x, name=None):
    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(a.dtype)

    return apply(f, _as_t(x))


def renorm(x, p, axis, max_norm, name=None):
    """Rescale every slice along `axis` whose p-norm exceeds max_norm down to
    exactly max_norm (reference renorm semantics, eps 1e-7)."""
    def f(a):
        reduce_axes = tuple(i for i in range(a.ndim) if i != (axis % a.ndim))
        norms = jnp.sum(jnp.abs(a) ** p, axis=reduce_axes, keepdims=True) \
            ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor.astype(a.dtype)

    return apply(f, _as_t(x))


def i0e(x, name=None):
    return _unary(jax.scipy.special.i0e, x, "i0e")


def i1(x, name=None):
    return _unary(jax.scipy.special.i1, x, "i1")


def i1e(x, name=None):
    return _unary(jax.scipy.special.i1e, x, "i1e")


def polygamma(x, n, name=None):
    def f(a):
        return jax.scipy.special.polygamma(n, a)

    return apply(f, _as_t(x), _op_name="polygamma")


def logit(x, eps=None, name=None):
    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jax.scipy.special.logit(a)

    return apply(f, _as_t(x), _op_name="logit")


def signbit(x, name=None):
    return _unary(jnp.signbit, x, "signbit")


def positive(x, name=None):
    return _as_t(x)


def dist(x, y, p=2, name=None):
    """p-norm of (x - y) (reference paddle.dist)."""
    def f(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum(d != 0).astype(a.dtype)
        if jnp.isinf(p):
            return (jnp.max(jnp.abs(d)) if p > 0
                    else jnp.min(jnp.abs(d))).astype(a.dtype)
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return apply(f, _as_t(x), _as_t(y), _op_name="dist")


def inverse(x, name=None):
    from .linalg import inv as _inv

    return _inv(x)


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor's elements (reference parity).
    Index enumeration happens host-side (shape depends only on len(x))."""
    import itertools

    import numpy as np

    n = int(_as_t(x).shape[0])
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), np.int32).reshape(-1, r)

    def f(a):
        return a[jnp.asarray(idx)]

    return apply(f, _as_t(x), _op_name="combinations")
