"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface on a jax/XLA/Pallas core.

Import as `import paddle_tpu as paddle` — the public namespace mirrors
`paddle.*` (see SURVEY.md for the layer map this implements). Subpackages are
lazy (PEP 562) so `import paddle_tpu` stays light.
"""

from __future__ import annotations

import importlib
import os as _os

__version__ = "0.1.0"


def _maybe_init_distributed():
    """Multi-process rendezvous MUST precede any XLA-backend touch, and
    importing this package touches the backend — so when the launcher's env
    contract is present (PADDLE_TRAINERS_NUM>1 + endpoints), join the
    jax.distributed coordination service here, before anything else. Scripts
    keep the reference shape: `import paddle; dist.init_parallel_env()`."""
    try:
        nproc = int(_os.getenv("PADDLE_TRAINERS_NUM",
                               _os.getenv("WORLD_SIZE", "1")))
        rank = int(_os.getenv("PADDLE_TRAINER_ID",
                              _os.getenv("RANK", "0")))
    except ValueError:
        return  # malformed contract: stay single-process, don't break import
    endpoints = _os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
    if nproc <= 1 or not endpoints:
        return
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=endpoints.split(",")[0],
            num_processes=nproc,
            process_id=rank,
        )
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" not in msg and "once" not in msg:
            raise


_maybe_init_distributed()

from .core.tensor import Tensor, Parameter, to_tensor
from .core.tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled
from .core.random import seed, get_rng_state, set_rng_state
from .core.dtype import (
    float16, bfloat16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128, get_default_dtype, set_default_dtype,
)
from .core.device import (
    set_device, get_device, device_count, is_compiled_with_cuda, synchronize,
)
from .core.flags import set_flags, get_flags
from .core.autograd_engine import grad

# the full tensor-op namespace is exported flat, paddle-style
from .tensor import *  # noqa: F401,F403
from . import tensor

_LAZY_SUBMODULES = (
    "analysis", "observability",
    "nn", "optimizer", "autograd", "amp", "io", "jit", "static", "device",
    "linalg", "fft", "vision", "distributed", "incubate", "profiler", "metric",
    "framework", "hapi", "models", "ops", "utils", "distribution", "sparse",
    "text", "audio", "onnx", "inference", "serving", "signal", "quantization",
    "regularizer", "version", "sysconfig", "geometric", "hub",
)

_LAZY_ATTRS = {
    "save": ("framework.io", "save"),
    "load": ("framework.io", "load"),
    "Model": ("hapi.model", "Model"),
    "Layer": ("nn.layer.layers", "Layer"),
    "summary": ("hapi.model_summary", "summary"),
    "flops": ("hapi.dynamic_flops", "flops"),
    "DataParallel": ("distributed.parallel", "DataParallel"),
    "LazyGuard": ("nn.initializer.lazy_init", "LazyGuard"),
    "callbacks": ("hapi", "callbacks"),
    "iinfo": ("framework.dtype_info", "iinfo"),
    "finfo": ("framework.dtype_info", "finfo"),
    "batch": ("io.reader_compat", "batch"),
}


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        modname, attr = _LAZY_ATTRS[name]
        mod = importlib.import_module(f".{modname}", __name__)
        val = getattr(mod, attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def disable_static(place=None):
    """Leave static-graph mode (back to the dygraph default)."""
    from .static.graph import disable_static as _ds

    _ds()
    return None


def enable_static():
    """Enter static-graph mode: static.data placeholders + lazy op
    recording + Executor.run (see paddle_tpu.static)."""
    from .static.graph import enable_static as _es

    _es()


def in_dynamic_mode():
    from .static.graph import in_static_mode

    return not in_static_mode()


def is_compiled_with_xpu():
    return False


def is_compiled_with_rocm():
    return False


def get_cudnn_version():
    return None
