"""paddle.device parity (ref: python/paddle/device/ (U))."""

from ..core.device import (
    set_device, get_device, get_default_device, device_count,
    is_compiled_with_cuda, is_compiled_with_tpu, synchronize, Place,
)


def get_available_device():
    import jax

    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    # XLA plays CINN's role and is always present
    return True


def is_compiled_with_distribute():
    return True


class cuda:
    """paddle.device.cuda stubs (no CUDA on the TPU build)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


class tpu:
    """TPU introspection — the CUDAPlace analog."""

    @staticmethod
    def device_count():
        import jax

        return sum(1 for d in jax.devices() if d.platform in ("tpu", "axon"))

    @staticmethod
    def is_available():
        return tpu.device_count() > 0

    @staticmethod
    def synchronize():
        synchronize()

    @staticmethod
    def memory_stats(device=None):
        import jax

        devs = [d for d in jax.devices() if d.platform in ("tpu", "axon")]
        if not devs:
            return {}
        try:
            return devs[0].memory_stats() or {}
        except Exception:
            return {}
