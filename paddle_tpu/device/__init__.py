"""paddle.device parity (ref: python/paddle/device/ (U))."""

from ..core.device import (
    set_device, get_device, get_default_device, device_count,
    is_compiled_with_cuda, is_compiled_with_tpu, synchronize, Place,
)


def get_available_device():
    import jax

    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    # XLA plays CINN's role and is always present
    return True


def is_compiled_with_distribute():
    return True


class cuda:
    """paddle.device.cuda stubs (no CUDA on the TPU build)."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


class tpu:
    """TPU introspection — the CUDAPlace analog."""

    @staticmethod
    def device_count():
        import jax

        return sum(1 for d in jax.devices() if d.platform in ("tpu", "axon"))

    @staticmethod
    def is_available():
        return tpu.device_count() > 0

    @staticmethod
    def synchronize():
        synchronize()

    @staticmethod
    def memory_stats(device=None):
        import jax

        devs = [d for d in jax.devices() if d.platform in ("tpu", "axon")]
        if not devs:
            return {}
        try:
            return devs[0].memory_stats() or {}
        except Exception:
            return {}


def get_all_device_type():
    import jax

    return sorted({d.platform for d in jax.devices()})


class Stream:
    """CUDA-stream shim: XLA owns scheduling on TPU; the API exists so
    reference scripts construct/synchronize streams as no-ops."""

    def __init__(self, device=None, priority=None):
        self.device = device

    def synchronize(self):
        import jax

        jax.effects_barrier() if hasattr(jax, "effects_barrier") else None

    def wait_event(self, event):
        return None

    def wait_stream(self, stream):
        return None

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        return None

    def query(self):
        return True

    def synchronize(self):
        return None


def stream_guard(stream):
    import contextlib

    return contextlib.nullcontext()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


class _StreamNS:
    Stream = Stream
    Event = Event
    stream_guard = staticmethod(stream_guard)
    current_stream = staticmethod(current_stream)
    set_stream = staticmethod(set_stream)


stream = _StreamNS()
