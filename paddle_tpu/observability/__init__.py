"""paddle_tpu.observability — unified metrics, structured event timeline,
and chrome-trace export across jit / training / serving.

The TPU-native rebuild of the reference's profiler subsystem's LIVE
half (SURVEY.md N20 host tracer + P26 Python Profiler): where
``paddle_tpu.profiler`` wraps ``jax.profiler`` device traces, this
package answers the production questions a device trace cannot —
"why did step time spike", "which function retraced", "how deep is the
serving queue" — from one process-wide place.

Three layers (see each module's docstring):

* :mod:`~paddle_tpu.observability.metrics` — typed Counter / Gauge /
  Histogram registry with label sets; ``snapshot()`` (nested JSON) and
  ``render_prometheus()`` (text exposition); absorbs the PR 2
  ``profiler.counters()`` provider registry.
* :mod:`~paddle_tpu.observability.events` — bounded ring-buffer
  structured event log with chrome-trace/Perfetto JSON export.
* :mod:`~paddle_tpu.observability.span` — ``span(name, **labels)``:
  one context manager emitting a ``jax.profiler.TraceAnnotation``, a
  histogram observation, and a begin/end timeline pair.

Phase 2 (request-scoped + externally visible):

* :mod:`~paddle_tpu.observability.tracing` — per-request
  :class:`RequestTrace` flight records in a bounded
  :class:`FlightRecorder` (all live + last-N finished), exportable as
  chrome async spans.
* :mod:`~paddle_tpu.observability.slo` — declared objectives over
  step-sized rolling windows; compliance, multi-window burn rate, and
  an overall ``slo_healthy`` readiness signal.
* :mod:`~paddle_tpu.observability.server` — stdlib HTTP exporter
  (``/metrics``, ``/healthz``, ``/readyz``, ``/debug/requests``,
  ``/debug/slo``, ``/debug/programs``, ``/trace``) on a background
  thread.

Phase 3 (the performance observatory):

* :mod:`~paddle_tpu.observability.profiling` — per-compiled-program
  cost cards (XLA cost/memory analysis, compile seconds, bucket
  metadata) in a process-wide :class:`ProgramCardRegistry`; the
  engine's cost model for per-request attribution.
* :mod:`~paddle_tpu.observability.memory` — device-memory ledger
  reconciling component-accounted bytes against ``jax.live_arrays()``
  (leak-detector delta) plus the backend-bandwidth probe behind the
  live achieved-vs-roofline gauge.
* :mod:`~paddle_tpu.observability.regression` — the bench-regression
  gate comparing a fresh bench run against the committed
  DECODE_BENCH.json (``check-bench`` CLI mode, run in CI; phase 4 adds
  ``--bench-file`` so MULTICHIP_BENCH.json rides the same gate).

Phase 4 (the mesh stack):

* :mod:`~paddle_tpu.observability.comms` — collective-comms ledger:
  a jaxpr walker counting collectives by (op, axis) with analytic
  ring-algorithm wire bytes, an ICI/DCN interconnect-bandwidth
  datasheet + modeled comms-seconds roofline, mesh telemetry
  (``/debug/mesh``, chrome-trace mesh stamp), and skew gauges
  (pipeline-bubble ratio, MoE expert-load imbalance).

Phase 5 (the fleet observatory):

* :mod:`~paddle_tpu.observability.loadgen` — seeded, fully
  deterministic workload traces (heavy-tailed lengths, MMPP bursty
  multi-tenant arrivals, Zipf shared-prefix populations, batch/
  deadline/abort mixes) with byte-identical serialization, a live
  HTTP/SSE replay harness against the serving gateway, and per-
  tenant/per-tier SLO-attainment rollups reconstructed from flight
  records.
* :mod:`~paddle_tpu.observability.fleetsim` — discrete-event fleet
  capacity simulator stepping the SAME trace through a modeled fleet
  (affinity routing, priority overtake bound, ProgramCard-derived
  service times against the backend datasheet): attainment-vs-
  replica-count curves plus the sim-vs-live calibration report
  FLEET_BENCH.json commits (``/debug/fleet``, CLI ``fleet`` mode).

CLI: ``python -m paddle_tpu.observability
{snapshot,prometheus,trace,programs,mesh,check-bench,fleet,serve}``.
"""

from __future__ import annotations

from . import (comms, events, fleetsim, loadgen, memory, metrics,
               profiling, regression, slo, tracing)
from .events import export_chrome_trace
from .fleetsim import ServiceModel
from .loadgen import SLOSpec, WorkloadSpec, WorkloadTrace
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    default_registry,
    gauge,
    histogram,
    render_prometheus,
    snapshot,
    validate_exposition,
    value,
)
from .memory import MemoryLedger
from .profiling import ProgramCard, ProgramCardRegistry
from .server import TelemetryServer
from .slo import Objective, SLOTracker
from .span import current_span, span, span_depth
from .tracing import FlightRecorder, RequestTrace

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "counter", "gauge", "histogram", "value",
    "default_registry", "snapshot", "render_prometheus",
    "validate_exposition",
    "events", "metrics", "span", "current_span", "span_depth",
    "export_chrome_trace", "reset",
    "slo", "tracing",
    "RequestTrace", "FlightRecorder", "Objective", "SLOTracker",
    "TelemetryServer",
    "comms", "memory", "profiling", "regression",
    "MemoryLedger", "ProgramCard", "ProgramCardRegistry",
    "loadgen", "fleetsim",
    "WorkloadSpec", "WorkloadTrace", "SLOSpec", "ServiceModel",
]


def reset():
    """Clear every metric value AND the event timeline (test isolation)."""
    metrics.reset()
    events.clear()
