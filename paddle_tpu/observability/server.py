"""Live telemetry endpoint: a stdlib ``http.server`` exporter on a
background thread.

This is the process's observability front door — the piece that turns
the in-process registry/event-ring/flight-recorder into something a
scraper, a load balancer, or a human with ``curl`` can reach while the
engine serves:

=================  ======================================================
path               payload
=================  ======================================================
``/metrics``       Prometheus text exposition (``render_prometheus()``)
``/healthz``       liveness — 200 the moment the thread serves
``/readyz``        readiness — 503 while the SLO tracker reports
                   unhealthy (multi-window burn), 200 otherwise; body
                   carries the per-objective burn snapshot either way
``/debug/requests``  flight-recorder JSON: all live + last-N finished
                   request traces
``/debug/slo``     full SLO tracker snapshot (objectives, windows,
                   compliance, burn rates)
``/debug/programs``  program-card registry JSON: per-compiled-program
                   FLOPs, bytes-accessed, compile seconds, bucket meta
``/debug/comms``   collective-comms ledger JSON: ``comms.*`` family
                   values + the interconnect datasheet
``/debug/mesh``    live ``HybridCommunicateGroup`` topology (axes,
                   dims, comm rank-lists) plus the comms ledger
``/debug/fleet``   latest fleet-observatory report (attainment curves,
                   calibration) — attach one via
                   ``TelemetryServer(fleet=...)``
``/trace``         chrome-trace JSON: process event ring merged with
                   per-request async spans (load in Perfetto)
``/``              tiny JSON index of the above
=================  ======================================================

Deliberately stdlib-only (``ThreadingHTTPServer`` on a daemon thread,
no framework, no new dependency) and deliberately read-only: every
route is a GET over data structures that already exist. ``port=0``
binds an ephemeral port (``.port`` reports the real one) so tests and
multi-engine processes never collide. The server holds REFERENCES to
the registry / recorder / SLO tracker, not the engine — an engine owns
and stops its server (``EngineConfig(telemetry_port=...)``), but the
server can outlive or predate any engine
(``python -m paddle_tpu.observability serve``).

Lifecycle: ``start()`` registers a ``telemetry.serverN`` provider on
its registry (the scrape endpoint is itself observable — up/port per
server); ``stop()`` unregisters it, shuts the listener down, and joins
the serving thread.  A server the owner forgets to stop still cleans
up at GC via ``weakref.finalize`` (the engine's provider pattern), so
repeated engine build/close cycles never accumulate stale providers.
"""

from __future__ import annotations

import json
import threading
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import comms as _comms
from . import events as _events
from . import metrics as _metrics
from . import profiling as _profiling

#: content type the Prometheus exposition format 0.0.4 mandates
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

ROUTES = ("/metrics", "/healthz", "/readyz", "/debug/requests",
          "/debug/slo", "/debug/programs", "/debug/comms",
          "/debug/mesh", "/debug/fleet", "/trace")


class TelemetryServer:
    """Background-thread HTTP exporter over the observability state.

    Parameters are all optional references: ``registry`` (default
    process registry), ``event_log`` (default process ring),
    ``recorder`` (a :class:`~.tracing.FlightRecorder`; without one
    ``/debug/requests`` serves an empty recorder view), ``slo`` (an
    :class:`~.slo.SLOTracker`; without one ``/readyz`` is always
    ready)."""

    def __init__(self, port=0, host="127.0.0.1", registry=None,
                 event_log=None, recorder=None, slo=None, fleet=None):
        self._host = host
        self._want_port = int(port)
        self.registry = registry
        self.event_log = event_log
        self.recorder = recorder
        self.slo = slo
        #: fleet-observatory report for ``/debug/fleet``: a dict, or a
        #: zero-arg callable returning the latest one (the CLI
        #: ``fleet`` mode and the replay harness attach theirs here)
        self.fleet = fleet
        self._httpd = None
        self._thread = None
        self._provider_name = None
        self._finalizer = None

    # ------------------------------------------------------------ plumbing
    def _registry(self):
        return self.registry or _metrics.default_registry()

    def _event_log(self):
        return self.event_log or _events.default_log()

    # ----------------------------------------------------------- lifecycle
    @property
    def running(self):
        return self._httpd is not None

    @property
    def port(self):
        """The actually-bound port (meaningful after ``start()``)."""
        return self._httpd.server_address[1] if self._httpd else None

    def url(self, path="/"):
        return f"http://{self._host}:{self.port}{path}"

    _instances = 0

    def start(self):
        """Bind and serve on a daemon thread; idempotent.  Registers a
        ``telemetry.serverN`` counter provider on the registry (the
        endpoint itself is observable) and arms a ``weakref.finalize``
        so an un-stopped server still unregisters and closes its
        socket when garbage-collected."""
        if self._httpd is not None:
            return self
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self._host, self._want_port),
                                          handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"telemetry:{self.port}", daemon=True)
        self._thread.start()
        TelemetryServer._instances += 1
        self._provider_name = f"telemetry.server{TelemetryServer._instances}"
        reg = self._registry()
        # the provider must not pin the server (mirror the engine's
        # weakref provider): a dead/stopped server reports nothing
        ref = weakref.ref(self)

        def _provider():
            srv = ref()
            if srv is None or srv._httpd is None:
                return {}
            return {"up": 1, "port": srv.port}

        reg.register_provider(self._provider_name, _provider)
        self._finalizer = weakref.finalize(
            self, _finalize_server, self._httpd, reg, self._provider_name)
        _events.instant("telemetry.start", cat="observability",
                        port=self.port)
        return self

    def stop(self):
        """Unregister the metrics provider, shut down the listener, and
        join the serving thread; idempotent."""
        if self._finalizer is not None:
            self._finalizer.detach()
            self._finalizer = None
        if self._provider_name is not None:
            self._registry().unregister_provider(self._provider_name)
            self._provider_name = None
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
        _events.instant("telemetry.stop", cat="observability")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ payloads
    def handle(self, path):
        """Route one GET; returns (status, content_type, body-bytes).
        Separated from the HTTP plumbing so tests can exercise routing
        without sockets."""
        path = path.split("?", 1)[0]
        if path == "/metrics":
            return 200, PROM_CONTENT_TYPE, self._registry(
                ).render_prometheus().encode()
        if path == "/healthz":
            return 200, "text/plain; charset=utf-8", b"ok\n"
        if path == "/readyz":
            ready = self.slo is None or self.slo.healthy
            body = {"ready": ready}
            if self.slo is not None:
                body["slo"] = self.slo.snapshot()
            return (200 if ready else 503), "application/json", _js(body)
        if path == "/debug/requests":
            payload = (self.recorder.to_json() if self.recorder is not None
                       else {"capacity": 0, "live_count": 0,
                             "finished_retained": 0, "finished_total": 0,
                             "dropped_finished": 0, "live": [],
                             "recent": []})
            return 200, "application/json", _js(payload)
        if path == "/debug/slo":
            payload = (self.slo.snapshot() if self.slo is not None
                       else {"tracker": None, "healthy": True,
                             "objectives": {}})
            return 200, "application/json", _js(payload)
        if path == "/debug/programs":
            return 200, "application/json", _js(_profiling.to_json())
        if path == "/debug/comms":
            return 200, "application/json", _js(_comms.to_json())
        if path == "/debug/mesh":
            return 200, "application/json", _js(_comms.mesh_json())
        if path == "/debug/fleet":
            payload = self.fleet() if callable(self.fleet) else self.fleet
            if payload is None:
                payload = {
                    "fleet": None,
                    "hint": "no fleet report attached — run `python -m "
                            "paddle_tpu.observability fleet` or pass "
                            "TelemetryServer(fleet=...)"}
            return 200, "application/json", _js(payload)
        if path == "/trace":
            extra = (self.recorder.chrome_events()
                     if self.recorder is not None else None)
            text = self._event_log().export_chrome_trace(extra=extra)
            return 200, "application/json", text.encode()
        if path == "/":
            return 200, "application/json", _js(
                {"service": "paddle_tpu.observability",
                 "endpoints": list(ROUTES)})
        return 404, "text/plain; charset=utf-8", b"not found\n"


def _js(obj):
    return (json.dumps(obj, indent=2, default=repr) + "\n").encode()


def _finalize_server(httpd, registry, provider_name):
    """GC fallback for a server that was never stop()ed: drop its
    provider and close the socket (must not reference the server —
    weakref.finalize callbacks that do would keep it alive forever)."""
    registry.unregister_provider(provider_name)
    try:
        httpd.shutdown()
        httpd.server_close()
    except Exception:                # pragma: no cover - interp exit
        pass


def _make_handler(server):
    # weakref, not a closure over the server: the serving thread holds
    # the httpd which holds this handler class — a strong ref here
    # would pin an abandoned server alive and its GC finalizer would
    # never fire
    ref = weakref.ref(server)

    class _Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            srv = ref()
            try:
                if srv is None:
                    raise RuntimeError("server shutting down")
                status, ctype, body = srv.handle(self.path)
            except Exception as e:  # never kill the serving thread
                status, ctype = 500, "text/plain; charset=utf-8"
                body = f"error: {type(e).__name__}: {e}\n".encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass  # scrapes are high-frequency; keep stderr quiet

    return _Handler


def serve(port=0, host="127.0.0.1", **refs):
    """Start and return a TelemetryServer (convenience for the CLI)."""
    return TelemetryServer(port=port, host=host, **refs).start()
