"""Request-scoped tracing: per-request flight records for the serving
engine.

Observability phase 1 (metrics/events/span) answers aggregate questions
— "what is TTFT p95", "how deep is the queue".  This module answers the
per-request one production debugging actually starts from: *what
happened to request 17381* — when it queued, which prefill batch
admitted it and how many prompt tokens the prefix cache served, every
decode horizon it rode (tokens emitted, speculative accept length), each
preemption/resume round-trip, and how it ended.

Two pieces:

* :class:`RequestTrace` — the flight record the engine attaches to a
  ``Request`` at submit.  A trace is a monotonic-clock event list of
  ``(kind, t, args)`` tuples; kinds are the engine's lifecycle
  transitions (``queued``/``prefill``/``first_token``/``decode``/
  ``preempt``/``resume``/``finish``/``abort``).  Appends are one tuple
  per lifecycle transition per request — O(1), no locks on the hot path
  (CPython list.append is atomic under the GIL; readers snapshot with
  ``list()``), so tracing rides decode without measurable overhead.
* :class:`FlightRecorder` — the bounded retention policy: ALL currently
  live traces are pinned (a live request must always be debuggable, no
  matter how old), plus a drop-oldest ring of the last N *finished*
  traces.  ``to_json()`` reconstructs everything as plain dicts for the
  ``/debug/requests`` telemetry endpoint; ``chrome_events()`` renders
  each trace as a per-request async span (``b``/``n``/``e`` with
  ``id=request_id``) in the Chrome Trace Event format, mergeable into
  :func:`events.export_chrome_trace` for Perfetto.

The event sequence is the engine's ground truth restated per request:
``sum(decode.tokens) + first_token`` equals the request's
``n_generated``, ``prefill.prefix_hit_tokens`` equals its prefix-cache
credit, and the preempt/resume pairs count its swap round-trips —
tested against the engine counters under continuous batching with
preemption and speculative decoding enabled.
"""

from __future__ import annotations

import collections
import json
import threading
import time

#: lifecycle event kinds, in the order a request may emit them
QUEUED = "queued"
GATEWAY = "gateway"          # accepted by the HTTP gateway: records the
                             # receive->queued admission hop (hop_s) plus
                             # tenant/priority — present only for requests
                             # that entered through the serving gateway
PREFILL = "prefill"          # first admission: batched fused prefill
FIRST_TOKEN = "first_token"  # sampled by the prefill dispatch (TTFT)
DECODE = "decode"            # one fused decode horizon this lane rode
PREEMPT = "preempt"          # swapped out under KV block pressure
SWAP_OUT = "swap_out"        # tiered KV: the preempted lane's block
                             # chain was saved into the host arena
                             # (records blocks + bytes moved) — always
                             # paired with the preceding PREEMPT
SWAP_IN = "swap_in"          # tiered KV: host-arena blocks were
                             # uploaded and re-bound for this request's
                             # re-admission instead of re-prefilled
                             # (records blocks, bytes, averted tokens)
RESUME = "resume"            # re-admission re-prefill after a preempt
FAILOVER = "failover"        # adopted from a dead replica: this trace's
                             # request resumes another engine's stream
                             # (records from_replica + resumed_tokens)
FINISH = "finish"            # retired: EOS or max-tokens
ABORT = "abort"              # cancelled by the caller

#: kinds that terminate a trace
TERMINAL = (FINISH, ABORT)

DEFAULT_CAPACITY = 256


class RequestTrace:
    """The flight record of one serving request.

    ``events`` is a list of ``(kind, t, args)`` tuples where ``t`` is
    seconds since the trace was created on the **monotonic** clock
    (durations between lifecycle events are exact even if the wall
    clock steps); ``wall0`` anchors the trace to wall time so exported
    chrome spans line up with the process event ring."""

    __slots__ = ("request_id", "engine", "wall0", "_mono0", "events")

    def __init__(self, request_id, engine=""):
        self.request_id = request_id
        self.engine = engine
        self.wall0 = time.time()
        self._mono0 = time.monotonic()
        self.events = []

    def add(self, kind, **args):
        """Append one lifecycle event (monotonic-stamped).  Returns the
        event's args dict: the engine patches dispatch-derived fields
        (program-card cost shares) into it after the compiled call,
        when the card is actually known."""
        self.events.append((kind, time.monotonic() - self._mono0, args))
        return args

    # ------------------------------------------------------------ queries
    def _snapshot(self):
        return list(self.events)

    @property
    def finished(self):
        evs = self._snapshot()
        return bool(evs) and evs[-1][0] in TERMINAL

    @property
    def duration_s(self):
        """Seconds from submit to the last recorded event."""
        evs = self._snapshot()
        return evs[-1][1] if evs else 0.0

    def counts(self):
        """Engine-counter view reconstructed from the event sequence
        alone: tokens emitted, prefix-hit tokens, preemptions, decode
        horizons ridden, speculative accepted tokens, and the request's
        cost bill — program-card FLOP/byte shares summed over every
        prefill/resume/decode dispatch it rode (the unit a fleet router
        or per-tenant quota bills against; summed across requests these
        reconstruct the engine's dispatch totals)."""
        tokens = prefix_hit = preempts = horizons = accepted = 0
        aborted = failovers = resumed_tokens = forced = 0
        swap_ins = swap_outs = swap_in_bytes = swap_out_bytes = 0
        flops = bytes_est = 0.0
        for kind, _, args in self._snapshot():
            if kind == FIRST_TOKEN:
                tokens += 1
            elif kind == ABORT:
                aborted += 1
            elif kind == DECODE:
                tokens += args.get("tokens", 0)
                accepted += args.get("accepted", 0)
                forced += args.get("forced", 0)
                horizons += 1
            elif kind in (PREFILL, RESUME):
                # last admission wins, matching the engine's
                # req.prefix_hit_tokens (overwritten on re-admission)
                prefix_hit = args.get("prefix_hit_tokens", prefix_hit)
            elif kind == PREEMPT:
                preempts += 1
            elif kind == SWAP_OUT:
                swap_outs += 1
                swap_out_bytes += args.get("bytes", 0)
            elif kind == SWAP_IN:
                swap_ins += 1
                swap_in_bytes += args.get("bytes", 0)
            elif kind == FAILOVER:
                # tokens resumed from the dead replica are NOT counted
                # as emitted by THIS trace's engine — per-engine sums
                # still reconcile against engine counters exactly
                failovers += 1
                resumed_tokens = args.get("resumed_tokens",
                                          resumed_tokens)
            if kind in (PREFILL, RESUME, DECODE):
                flops += args.get("flops_est", 0.0)
                bytes_est += args.get("bytes_est", 0.0)
        return {"tokens_emitted": tokens, "prefix_hit_tokens": prefix_hit,
                "preemptions": preempts, "decode_horizons": horizons,
                "spec_accepted_tokens": accepted,
                "spec_forced_tokens": forced, "aborted": aborted,
                "failovers": failovers, "resumed_tokens": resumed_tokens,
                "swap_ins": swap_ins, "swap_outs": swap_outs,
                "swap_in_bytes": swap_in_bytes,
                "swap_out_bytes": swap_out_bytes,
                "flops_est": flops, "bytes_est": bytes_est}

    def to_json(self):
        """Plain-dict reconstruction (the /debug/requests payload)."""
        evs = self._snapshot()
        return {
            "request_id": self.request_id,
            "engine": self.engine,
            "submit_wall_time": self.wall0,
            "finished": bool(evs) and evs[-1][0] in TERMINAL,
            "duration_s": round(evs[-1][1], 6) if evs else 0.0,
            "counts": self.counts(),
            "events": [dict(args, kind=kind, t=round(t, 6))
                       for kind, t, args in evs],
        }

    def chrome_events(self):
        """This trace as one async span in the Chrome Trace Event
        format: ``b`` at submit, an async instant (``n``) per lifecycle
        event, and ``e`` at the terminal event (open-ended while the
        request is live).  All share ``id=request_id`` so Perfetto draws
        one row per request."""
        import os

        pid = os.getpid()
        rid = str(self.request_id)
        base = {"cat": "serving.request", "pid": pid, "tid": 0,
                "id": rid}
        out = [dict(base, name=f"request {rid}", ph="b",
                    ts=self.wall0 * 1e6,
                    args={"engine": self.engine})]
        for kind, t, args in self._snapshot():
            ts = (self.wall0 + t) * 1e6
            out.append(dict(base, name=kind, ph="n", ts=ts,
                            args={k: _jsonable(v)
                                  for k, v in args.items()}))
            if kind in TERMINAL:
                out.append(dict(base, name=f"request {rid}", ph="e",
                                ts=ts))
        return out


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


class FlightRecorder:
    """Bounded retention over request traces: every LIVE trace is
    pinned (attach/finish bracket a request's life), finished traces
    fall off a drop-oldest ring of ``capacity``.  Thread-safe: the
    engine writes from its driving thread, the telemetry server reads
    from its own."""

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._live = {}                    # request_id -> RequestTrace
        self._done = collections.deque(maxlen=int(capacity))
        self._dropped = 0
        self._finished_total = 0

    @property
    def capacity(self):
        return self._done.maxlen

    @property
    def dropped(self):
        """Finished traces that fell off the retention ring."""
        return self._dropped

    def attach(self, trace):
        """Register a live trace (called at submit)."""
        with self._lock:
            self._live[trace.request_id] = trace
        return trace

    def finish(self, trace):
        """Move a trace from the live set to the finished ring (called
        at retire/abort).  Unknown traces are adopted — a recorder can
        be swapped in mid-flight."""
        with self._lock:
            self._live.pop(trace.request_id, None)
            if len(self._done) == self._done.maxlen:
                self._dropped += 1
            self._done.append(trace)
            self._finished_total += 1

    def live(self):
        """All currently-live traces (always fully retained)."""
        with self._lock:
            return list(self._live.values())

    def recent(self):
        """The retained finished traces, oldest first."""
        with self._lock:
            return list(self._done)

    def get(self, request_id):
        with self._lock:
            if request_id in self._live:
                return self._live[request_id]
            for tr in self._done:
                if tr.request_id == request_id:
                    return tr
        return None

    def to_json(self):
        return {
            "capacity": self.capacity,
            "live_count": len(self._live),
            "finished_retained": len(self._done),
            "finished_total": self._finished_total,
            "dropped_finished": self._dropped,
            "live": [t.to_json() for t in self.live()],
            "recent": [t.to_json() for t in self.recent()],
        }

    def chrome_events(self):
        """Per-request async spans for every retained trace, mergeable
        into ``events.export_chrome_trace(extra=...)``."""
        out = []
        for tr in self.recent() + self.live():
            out.extend(tr.chrome_events())
        return out

    def export_chrome_trace(self, file=None):
        """Standalone chrome-trace document of the retained traces."""
        doc = {
            "traceEvents": sorted(self.chrome_events(),
                                  key=lambda e: e["ts"]),
            "displayTimeUnit": "ms",
            "metadata": {"producer": "paddle_tpu.observability.tracing",
                         "dropped_finished_traces": self._dropped},
        }
        text = json.dumps(doc)
        if file is not None:
            if hasattr(file, "write"):
                file.write(text)
            else:
                with open(file, "w") as f:
                    f.write(text)
        return text

    def clear(self):
        with self._lock:
            self._live.clear()
            self._done.clear()
            self._dropped = 0
            self._finished_total = 0
