"""Trace-driven load generation: deterministic, production-shaped
workload traces plus a live-gateway replay harness.

The fleet observatory's first half (observability phase 5).  A
:class:`WorkloadSpec` describes a traffic shape the way capacity
planners do — arrival process, length distributions, tenant mix,
prefix reuse, admission tiers — and :func:`generate` expands it into a
concrete :class:`WorkloadTrace`:

* **heavy-tailed lengths** — prompt lengths are lognormal (median ×
  ``exp(sigma * N(0,1))``, clipped), output budgets are Pareto
  (``xm * (1 + Pareto(alpha))``, clipped): a few long requests dominate
  token volume, as in production;
* **bursty arrivals** — a 2-state Markov-modulated Poisson process
  (calm/burst states with exponential dwell, the burst state multiplies
  the rate by ``burst_factor``), so inter-arrival times are
  overdispersed (CV > 1), not memoryless;
* **shared-prefix populations** — each request draws a "system prompt"
  population from a Zipf over ``n_prefix_populations`` and prepends
  that population's fixed ``prefix_len`` tokens, so the radix cache and
  the router's prefix affinity see realistic reuse skew;
* **multi-tenant mix** — tenants drawn from their own Zipf;
* **admission mixes** — a priority distribution over interactive
  tiers, a ``deadline_fraction`` with uniform deadlines, an
  ``abort_fraction`` applied to BURST-state arrivals only (an "abort
  storm": clients hang up exactly when the system is busiest), and a
  ``batch_fraction`` routed to the offline batch lane
  (``priority=-1``, non-streaming, no deadline — interactive traffic
  overtakes it without bound).

Determinism is the contract: generation draws every random variate
from one seeded ``numpy`` Generator, uses **virtual time** only (no
wall-clock reads, per the PTA513 doctrine), and serializes through
:meth:`WorkloadTrace.to_json` as canonical JSON (sorted keys, fixed
separators, rounded floats) — the same seed produces a byte-identical
trace in any process, so a trace digest pins a benchmark's workload
the way a git SHA pins its code.

The second half is :func:`replay`: drive a generated trace against a
LIVE serving gateway over real HTTP/SSE (``speed`` compresses virtual
time so a 5-minute trace replays in seconds), then reconstruct
per-phase latency — queue wait, prefill/TTFT, decode TPOT — from the
engines' RequestTrace flight records and aggregate SLO attainment per
tenant and per priority tier with :func:`summarize`.  The same
``summarize`` consumes the capacity simulator's output
(:mod:`~paddle_tpu.observability.fleetsim`), so sim-vs-live
calibration compares like with like.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass

import numpy as np

#: canonical trace-document format tag (bump on incompatible change)
TRACE_FORMAT = "paddle_tpu.workload_trace/1"

#: aggregation label of the offline batch lane (``priority < 0``)
BATCH_TIER = "batch"


def tier_of(priority):
    """Aggregation tier of a priority: ``"batch"`` for the offline
    lane, ``"p<N>"`` for interactive tiers."""
    p = int(priority)
    return BATCH_TIER if p < 0 else f"p{p}"


# --------------------------------------------------------------------- spec
@dataclass(frozen=True)
class WorkloadSpec:
    """One traffic shape, fully determined by its fields + ``seed``.

    Defaults are sized for the CPU-proxy engines the test/CI fleet
    runs (``max_seq_len=64``): ``prompt_len_max + max_new_tokens_cap``
    stays within a tiny engine's sequence budget."""

    seed: int = 0
    n_requests: int = 64
    # ---- arrivals: 2-state Markov-modulated Poisson (virtual seconds)
    rate_rps: float = 8.0
    burst_factor: float = 4.0
    calm_dwell_s: float = 4.0
    burst_dwell_s: float = 1.0
    # ---- tenant mix (Zipf-skewed: tenant0 is the whale)
    n_tenants: int = 3
    tenant_zipf_a: float = 1.2
    # ---- heavy-tailed lengths
    prompt_len_median: int = 12
    prompt_len_sigma: float = 0.7
    prompt_len_max: int = 40
    output_pareto_xm: float = 3.0
    output_pareto_alpha: float = 2.0
    max_new_tokens_cap: int = 12
    # ---- shared-prefix populations (Zipf over system prompts)
    n_prefix_populations: int = 8
    prefix_zipf_a: float = 1.3
    prefix_len: int = 8
    # ---- admission mixes
    priority_levels: tuple = (0, 1, 2)
    priority_weights: tuple = (0.7, 0.2, 0.1)
    #: fraction routed to the offline batch lane (priority=-1, no SSE)
    batch_fraction: float = 0.0
    deadline_fraction: float = 0.0
    deadline_min_s: float = 0.5
    deadline_max_s: float = 4.0
    #: abort storm: this fraction of BURST-state interactive arrivals
    #: disconnect ``abort_after_s`` (virtual) after submit
    abort_fraction: float = 0.0
    abort_after_s: float = 0.25
    #: prompt token ids are drawn uniformly from [0, vocab)
    vocab: int = 120

    def validate(self):
        if self.n_requests < 1:
            raise ValueError("n_requests must be >= 1")
        if not self.rate_rps > 0 or not self.burst_factor >= 1:
            raise ValueError("need rate_rps > 0 and burst_factor >= 1")
        if self.prefix_len < 1 or self.prompt_len_max <= self.prefix_len:
            raise ValueError("need prompt_len_max > prefix_len >= 1")
        if len(self.priority_levels) != len(self.priority_weights):
            raise ValueError("priority_levels/priority_weights length "
                             "mismatch")
        if any(int(p) < 0 for p in self.priority_levels):
            raise ValueError("priority_levels are interactive tiers "
                             "(>= 0); the batch lane comes from "
                             "batch_fraction")
        for f in ("batch_fraction", "deadline_fraction",
                  "abort_fraction"):
            v = getattr(self, f)
            if not 0.0 <= float(v) <= 1.0:
                raise ValueError(f"{f} must be in [0, 1], got {v}")
        return self


@dataclass
class WorkloadRequest:
    """One generated request: everything a replay client or the
    simulator needs, in virtual time."""

    index: int
    #: virtual seconds from trace start (divide by the replay
    #: ``speed`` for wall seconds)
    t_submit: float
    tenant: str
    #: -1 = offline batch lane; >= 0 interactive
    priority: int
    prompt_ids: list
    #: leading tokens shared with every request of ``prefix_pop``
    prefix_len: int
    prefix_pop: int
    max_new_tokens: int
    deadline_s: float | None
    #: virtual seconds after submit at which the client hangs up
    #: (None = patient client)
    abort_after_s: float | None
    #: interactive requests stream over SSE; the batch lane does not
    stream: bool
    #: True when the MMPP was in its burst state at arrival
    arrived_in_burst: bool

    @property
    def tier(self):
        return tier_of(self.priority)

    @property
    def prompt_len(self):
        return len(self.prompt_ids)


class WorkloadTrace:
    """A generated workload: the spec it came from plus its concrete
    request list, with canonical byte-stable serialization."""

    def __init__(self, spec, requests):
        self.spec = spec
        self.requests = list(requests)

    def __len__(self):
        return len(self.requests)

    @property
    def duration_s(self):
        """Virtual seconds from trace start to the last submit."""
        return self.requests[-1].t_submit if self.requests else 0.0

    def to_json(self):
        """Canonical serialization: sorted keys, minimal separators,
        floats pre-rounded at generation — the same spec+seed is
        byte-identical across processes (tested via subprocess)."""
        doc = {"format": TRACE_FORMAT,
               "spec": dataclasses.asdict(self.spec),
               "requests": [dataclasses.asdict(r)
                            for r in self.requests]}
        return json.dumps(doc, sort_keys=True, separators=(",", ":"))

    def digest(self):
        """sha256 of the canonical serialization — the workload's
        provenance stamp (FLEET_BENCH rows carry it)."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()

    @classmethod
    def from_json(cls, text):
        doc = json.loads(text)
        if doc.get("format") != TRACE_FORMAT:
            raise ValueError(
                f"not a workload trace document "
                f"(format={doc.get('format')!r})")
        sd = dict(doc["spec"])
        sd["priority_levels"] = tuple(sd["priority_levels"])
        sd["priority_weights"] = tuple(sd["priority_weights"])
        return cls(WorkloadSpec(**sd),
                   [WorkloadRequest(**r) for r in doc["requests"]])


def _zipf_weights(n, a):
    w = 1.0 / np.arange(1, n + 1, dtype=float) ** float(a)
    return w / w.sum()


def generate(spec):
    """Expand a :class:`WorkloadSpec` into a concrete
    :class:`WorkloadTrace`.  Every variate comes from one seeded PCG64
    Generator and all times are virtual — no wall-clock reads."""
    spec.validate()
    rng = np.random.default_rng(int(spec.seed))
    # shared-prefix populations: each "system prompt" is a fixed token
    # run drawn once, so same-population requests share radix-cache
    # blocks and hash to the same affinity key
    prefixes = [[int(t) for t in
                 rng.integers(0, spec.vocab, size=spec.prefix_len)]
                for _ in range(spec.n_prefix_populations)]
    pop_p = _zipf_weights(spec.n_prefix_populations, spec.prefix_zipf_a)
    ten_p = _zipf_weights(spec.n_tenants, spec.tenant_zipf_a)
    pri_p = np.asarray(spec.priority_weights, dtype=float)
    pri_p = pri_p / pri_p.sum()

    t = 0.0
    in_burst = False
    state_left = float(rng.exponential(spec.calm_dwell_s))
    requests = []
    for i in range(spec.n_requests):
        # MMPP: draw the next arrival, crossing state boundaries as
        # the exponential dwell expires
        while True:
            rate = spec.rate_rps * (spec.burst_factor if in_burst
                                    else 1.0)
            gap = float(rng.exponential(1.0 / rate))
            if gap <= state_left:
                state_left -= gap
                t += gap
                break
            t += state_left
            in_burst = not in_burst
            state_left = float(rng.exponential(
                spec.burst_dwell_s if in_burst else spec.calm_dwell_s))
        tenant = f"tenant{int(rng.choice(spec.n_tenants, p=ten_p))}"
        pop = int(rng.choice(spec.n_prefix_populations, p=pop_p))
        plen = int(np.clip(
            round(spec.prompt_len_median
                  * float(np.exp(rng.normal(0.0, spec.prompt_len_sigma)))),
            spec.prefix_len + 1, spec.prompt_len_max))
        suffix = [int(x) for x in
                  rng.integers(0, spec.vocab, size=plen - spec.prefix_len)]
        budget = int(np.clip(
            round(spec.output_pareto_xm
                  * (1.0 + float(rng.pareto(spec.output_pareto_alpha)))),
            1, spec.max_new_tokens_cap))
        if float(rng.random()) < spec.batch_fraction:
            priority, deadline, abort_after, stream = -1, None, None, False
        else:
            priority = int(spec.priority_levels[int(
                rng.choice(len(spec.priority_levels), p=pri_p))])
            deadline = (round(float(rng.uniform(
                spec.deadline_min_s, spec.deadline_max_s)), 6)
                if float(rng.random()) < spec.deadline_fraction else None)
            abort_after = (float(spec.abort_after_s)
                           if in_burst
                           and float(rng.random()) < spec.abort_fraction
                           else None)
            stream = True
        requests.append(WorkloadRequest(
            index=i, t_submit=round(t, 6), tenant=tenant,
            priority=priority, prompt_ids=prefixes[pop] + suffix,
            prefix_len=spec.prefix_len, prefix_pop=pop,
            max_new_tokens=budget, deadline_s=deadline,
            abort_after_s=abort_after, stream=stream,
            arrived_in_burst=in_burst))
    return WorkloadTrace(spec, requests)


# ------------------------------------------------------- workload shapes
def chat_heavy(seed=0, n_requests=64, **overrides):
    """Interactive chat fleet: no batch lane, deadline and abort-storm
    mixes on."""
    kw = dict(seed=seed, n_requests=n_requests, batch_fraction=0.0,
              deadline_fraction=0.2, abort_fraction=0.15)
    kw.update(overrides)
    return WorkloadSpec(**kw)


def mixed_chat_batch(seed=0, n_requests=64, **overrides):
    """Mixed fleet: a third of traffic rides the offline batch lane
    (priority=-1, non-streaming) under the same interactive foreground."""
    kw = dict(seed=seed, n_requests=n_requests, batch_fraction=0.35,
              deadline_fraction=0.15, abort_fraction=0.1)
    kw.update(overrides)
    return WorkloadSpec(**kw)


def calibration_probe(seed=0, n_requests=32, **overrides):
    """Gentle, deterministic-outcome workload for sim-vs-live
    calibration: no client aborts and no deadlines (both race the wall
    clock, so their outcome flips run-to-run near the boundary and
    would make the calibration gate flaky), mild arrival rate.  The
    calibration regime is deliberately UNCONTENDED — on a shared-core
    CI host, co-located replicas cannot beat one replica once host
    compute saturates, so the live side can only certify the service-
    time model where queueing, not the host, is the story."""
    kw = dict(seed=seed, n_requests=n_requests, rate_rps=6.0,
              burst_factor=2.0, batch_fraction=0.0,
              deadline_fraction=0.0, abort_fraction=0.0)
    kw.update(overrides)
    return WorkloadSpec(**kw)


#: named shapes the CLI ``fleet`` mode exposes
SHAPES = {"chat": chat_heavy, "mixed": mixed_chat_batch,
          "calib": calibration_probe}


# ------------------------------------------------------------ SLO + rollup
@dataclass(frozen=True)
class SLOSpec:
    """Attainment thresholds, in wall seconds at replay speed.  A
    request ATTAINS when it completed (not shed/aborted/expired) with
    ``ttft_s`` and ``tpot_s`` within threshold; batch-lane requests
    attain on completion alone (throughput tier, no latency SLO)."""

    ttft_s: float = 2.0
    tpot_s: float = 0.5


def _attains(rec, slo):
    if not rec.get("completed"):
        return False
    if rec.get("tier") == BATCH_TIER:
        return True
    ttft = rec.get("ttft_s")
    if ttft is None or ttft > slo.ttft_s:
        return False
    tpot = rec.get("tpot_s")
    return tpot is None or tpot <= slo.tpot_s


def _pctl(values, q):
    if not values:
        return None
    return round(float(np.percentile(np.asarray(values, float), q)), 6)


def summarize(records, slo=None):
    """Roll normalized per-request records (replay or sim) into the
    fleet report: counts, shed/abort/deadline rates, per-phase latency
    percentiles, prefix hit ratio, and SLO attainment overall, per
    tenant, and per priority tier.

    A record is a dict with: ``tenant``, ``tier``, ``completed``,
    ``status`` (HTTP code or sim disposition), ``shed``, ``aborted``,
    ``deadline_expired``, ``queue_s``/``ttft_s``/``tpot_s`` (None when
    unknown), ``tokens``, ``prompt_tokens``, ``prefix_hit_tokens``."""
    slo = slo or SLOSpec()
    records = list(records)
    n = len(records)
    done = [r for r in records if r.get("completed")]
    shed = sum(1 for r in records if r.get("shed"))
    aborted = sum(1 for r in records if r.get("aborted"))
    expired = sum(1 for r in records if r.get("deadline_expired"))
    prompt_tok = sum(r.get("prompt_tokens", 0) for r in done)
    hit_tok = sum(r.get("prefix_hit_tokens", 0) for r in done)

    def _phase(key):
        vals = [r[key] for r in records if r.get(key) is not None]
        return {"p50": _pctl(vals, 50), "p95": _pctl(vals, 95),
                "max": _pctl(vals, 100), "n": len(vals)}

    def _group(keyfn):
        out = {}
        for r in records:
            g = out.setdefault(keyfn(r), {"requests": 0, "completed": 0,
                                          "tokens": 0, "shed": 0,
                                          "attained": 0})
            g["requests"] += 1
            g["completed"] += int(bool(r.get("completed")))
            g["tokens"] += int(r.get("tokens", 0))
            g["shed"] += int(bool(r.get("shed")))
            g["attained"] += int(_attains(r, slo))
        for g in out.values():
            g["attainment"] = round(g["attained"] / g["requests"], 6)
        return dict(sorted(out.items()))

    attained = sum(1 for r in records if _attains(r, slo))
    return {
        "requests": n,
        "completed": len(done),
        "shed": shed,
        "aborted": aborted,
        "deadline_expired": expired,
        "tokens_total": sum(r.get("tokens", 0) for r in records),
        "prefix_hit_ratio": (round(hit_tok / prompt_tok, 6)
                             if prompt_tok else 0.0),
        "phase_latency": {"queue_s": _phase("queue_s"),
                          "ttft_s": _phase("ttft_s"),
                          "tpot_s": _phase("tpot_s")},
        "slo": {"ttft_s": slo.ttft_s, "tpot_s": slo.tpot_s},
        "attainment": round(attained / n, 6) if n else 1.0,
        "per_tenant": _group(lambda r: r.get("tenant", "")),
        "per_tier": _group(lambda r: r.get("tier", "p0")),
    }


# ------------------------------------------------------------- live replay
def _phase_from_events(events):
    """Reconstruct (queue_s, ttft_s, tpot_s, tokens, prefix_hits) from
    one flight-record event list (``RequestTrace.to_json()['events']``):
    queue wait is submit -> first prefill admission, TTFT is submit ->
    first sampled token, TPOT averages the decode span over the tokens
    it emitted."""
    t_admit = t_first = t_last = None
    tokens = 0
    prefix_hits = 0
    for ev in events:
        kind, t = ev.get("kind"), ev.get("t", 0.0)
        if kind == "prefill" and t_admit is None:
            t_admit = t
            prefix_hits = ev.get("prefix_hit_tokens", prefix_hits)
        elif kind == "first_token":
            if t_first is None:
                t_first = t
            tokens += 1
            t_last = t
        elif kind == "decode":
            tokens += ev.get("tokens", 0)
            t_last = t
    tpot = None
    if t_first is not None and t_last is not None and tokens > 1:
        tpot = (t_last - t_first) / (tokens - 1)
    return t_admit, t_first, tpot, tokens, prefix_hits


def fleet_flight_records(gateway):
    """Per-request flight records across every replica's engine
    recorder, as ``RequestTrace.to_json()`` dicts (the replay hook the
    phase reconstruction reads)."""
    out = []
    for w in gateway.workers:
        rec = getattr(getattr(w, "engine", None), "recorder", None)
        if rec is None:
            continue
        doc = rec.to_json()
        out.extend(doc["recent"])
        out.extend(doc["live"])
    return out


def replay(trace, gateway, speed=20.0, slo=None, timeout_s=60.0):
    """Replay a trace against a STARTED gateway over real HTTP/SSE.

    One client thread per request sleeps until its (speed-compressed)
    submit time, POSTs ``/v1/completions`` — SSE for interactive,
    blocking JSON for the batch lane — and records status, streamed
    token ids, client-side TTFT, and disposition.  Requests with
    ``abort_after_s`` close their connection mid-stream (the abort
    storm).  After the last response, per-phase latencies are
    reconstructed from the engines' flight records and rolled up with
    :func:`summarize`; the returned report carries the raw per-request
    records under ``"records"`` (token ids under ``"token_ids"``) for
    parity checks and reconciliation."""
    import http.client
    import threading
    import time

    if not getattr(gateway, "running", False):
        raise RuntimeError("replay needs a started gateway")
    speed = float(speed)
    if speed <= 0:
        raise ValueError("speed must be > 0")
    host, port = gateway.config.host, gateway.port
    model_id = gateway.config.model_id
    records = [None] * len(trace.requests)
    t0 = time.monotonic()

    def _client(req):
        rec = {"index": req.index, "tenant": req.tenant,
               "tier": req.tier, "priority": req.priority,
               "prompt_tokens": req.prompt_len, "tokens": 0,
               "completed": False, "shed": False, "aborted": False,
               "deadline_expired": False, "queue_s": None,
               "ttft_s": None, "tpot_s": None, "token_ids": [],
               "prefix_hit_tokens": 0}
        records[req.index] = rec
        delay = req.t_submit / speed - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        payload = {"model": model_id, "prompt": req.prompt_ids,
                   "max_tokens": req.max_new_tokens,
                   "temperature": 0.0, "tenant": req.tenant,
                   "priority": req.priority, "stream": req.stream}
        if req.deadline_s is not None:
            payload["deadline_s"] = req.deadline_s / speed
        conn = http.client.HTTPConnection(host, port,
                                          timeout=timeout_s)
        t_send = time.monotonic()
        try:
            conn.request("POST", "/v1/completions",
                         json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            rec["status"] = resp.status
            if resp.status != 200:
                body = json.loads(resp.read() or b"{}")
                rec["error"] = body.get("error", {}).get("code")
                rec["shed"] = resp.status in (429, 503)
                return
            if not req.stream:
                body = json.loads(resp.read())
                choice = body["choices"][0]
                rec["token_ids"] = list(choice["token_ids"])
                rec["tokens"] = len(rec["token_ids"])
                reason = choice["finish_reason"]
                rec["aborted"] = reason == "abort"
                rec["completed"] = not rec["aborted"]
                return
            cutoff = (t_send + req.abort_after_s / speed
                      if req.abort_after_s is not None else None)
            reason = None
            while True:
                if cutoff is not None and time.monotonic() > cutoff:
                    rec["aborted"] = True   # client hangs up mid-storm
                    return
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    break
                choice = json.loads(data)["choices"][0]
                ids = choice["token_ids"]
                if ids and rec["ttft_s"] is None:
                    rec["ttft_s"] = time.monotonic() - t_send
                rec["token_ids"].extend(int(i) for i in ids)
                if choice["finish_reason"] is not None:
                    reason = choice["finish_reason"]
            rec["tokens"] = len(rec["token_ids"])
            rec["aborted"] = reason == "abort"
            rec["deadline_expired"] = (rec["aborted"]
                                       and req.deadline_s is not None)
            rec["completed"] = reason in ("stop", "length")
        except Exception as e:  # client-side failure is a record, not
            rec["error"] = f"{type(e).__name__}: {e}"   # a crash
        finally:
            conn.close()

    threads = [threading.Thread(target=_client, args=(r,), daemon=True)
               for r in trace.requests]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=timeout_s + trace.duration_s / speed)

    # phase reconstruction from the engines' flight records: match by
    # per-request identity (tenant + prompt length + token count is
    # ambiguous, so match the whole output stream where possible)
    flights = fleet_flight_records(gateway)
    by_stream = {}
    for fl in flights:
        q, ttft, tpot, toks, hits = _phase_from_events(fl["events"])
        by_stream.setdefault(
            (fl["counts"]["tokens_emitted"],), []).append(
                {"queue_s": q, "ttft_s": ttft, "tpot_s": tpot,
                 "prefix_hit_tokens": hits, "flight": fl})
    for rec in records:
        if rec is None or not rec.get("completed"):
            continue
        pool = by_stream.get((rec["tokens"],))
        if pool:
            ph = pool.pop(0)
            rec["queue_s"] = ph["queue_s"]
            if rec["ttft_s"] is None:
                rec["ttft_s"] = ph["ttft_s"]
            rec["tpot_s"] = ph["tpot_s"]
            rec["prefix_hit_tokens"] = ph["prefix_hit_tokens"]

    report = summarize([r for r in records if r is not None], slo=slo)
    report["speed"] = speed
    report["trace_digest"] = trace.digest()
    report["records"] = [r for r in records if r is not None]
    return report


def reconcile_tokens(gateway, report):
    """Token-conservation check between a replay report and the
    engines themselves: client-streamed tokens (completed requests),
    flight-record emitted tokens, and the engines' per-tenant ledger
    must tell one story.  Returns the three totals; on a drain-clean
    fleet with no client aborts they are equal."""
    client = sum(r.get("tokens", 0) for r in report["records"]
                 if r.get("completed"))
    flight = sum(fl["counts"]["tokens_emitted"]
                 for fl in fleet_flight_records(gateway))
    ledger = 0
    for w in gateway.workers:
        eng = getattr(w, "engine", None)
        if eng is None:
            continue
        for counts in eng.tenant_ledger().values():
            ledger += counts.get("tokens_generated", 0)
    return {"client_tokens": client, "flight_tokens": flight,
            "ledger_tokens": ledger}
