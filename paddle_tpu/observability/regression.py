"""Bench-regression gate: DECODE_BENCH.json as an enforced contract
(observability phase 3).

The bench trajectory accumulated across PRs (tok/s, KV bytes/step,
compile counts, TTFT) was advisory until now — a PR could silently
regress any row and CI stayed green.  This module compares a FRESH
bench run (``benchmarks/bench_decode.py --only <section> --out f.json``)
against the committed DECODE_BENCH.json and fails on regressions:

* rows pair by exact ``metric`` string, which embeds the backend tag —
  a cpu run never gates against a tpu row;
* direction comes from the row's ``unit``: ``tokens/s`` and capacity
  ratios regress DOWN, latency (``ms``) regresses UP;
* the primary ``value`` is timing-derived and noisy, so it gets a
  configurable relative ``tolerance`` (CI on shared cpu runners wants
  a generous one);
* deterministic per-row fields — KV bytes per step, compile counts,
  dispatch counts — are pure functions of the code, so they gate at
  ``det_tolerance`` (default exact): a paged-attention change that
  doubles KV traffic fails even if tok/s noise hides it;
* an explicit ``allow_regress`` substring list acknowledges intended
  regressions (e.g. a PR that trades decode speed for capacity) —
  allowed findings are reported but don't fail the gate.

``python -m paddle_tpu.observability check-bench`` is the CLI; CI runs
it against a tiny ``--only`` section per push.
"""

from __future__ import annotations

import json

#: deterministic per-row fields gated at det_tolerance, with their
#: regression direction (False = lower is better, True = higher is
#: better).  All byte/compile/dispatch counts regress UP.
DETERMINISTIC_FIELDS = {
    "kv_bytes_read_per_step": False,
    "kv_bytes_per_block": False,
    "weight_bytes": False,
    "decode_compiles": False,
    "prefill_compiles": False,
    "prefill_dispatches": False,
    "host_syncs": False,
    "tokens_per_gb_kv_read": True,
    # phase 4: collective counts from the jaxpr comms walker are pure
    # functions of (program, mesh shape) — an extra all_gather per step
    # gates exact even when step-time noise hides it
    "psum_calls": False,
    "pmax_calls": False,
    "pmin_calls": False,
    "all_gather_calls": False,
    "psum_scatter_calls": False,
    "all_to_all_calls": False,
    "ppermute_calls": False,
    "collective_calls_total": False,
    "modeled_wire_bytes_per_step": False,
    # chunked prefill: how many chunk dispatches a long prompt takes
    # and the chunk bucket itself are schedule facts, not timings — a
    # change that silently doubles per-boundary prefill work (or stops
    # chunking at all) gates exact even when the stall numbers are
    # noise-bound on cpu runners
    "chunk_dispatches": False,
    "chunk_tokens": False,
    "max_dispatch_bucket": False,
    # tiered KV: how many blocks/bytes a swap round-trip moves is a
    # pure function of (context length, block size, store dtype) — a
    # change that silently fattens the host<->device payload (or stops
    # swapping and falls back to recompute) gates exact even when the
    # crossover timings are noise-bound; averted tokens gate UP (fewer
    # re-prefilled tokens per swap-in is the whole point)
    "swap_ins": True,
    "swap_outs": True,
    "swap_in_blocks": False,
    "swap_out_blocks": False,
    "swap_in_bytes": False,
    "swap_out_bytes": False,
    "swap_averted_tokens": True,
}


def higher_is_better(unit):
    """Regression direction from a row's unit string: throughput and
    capacity regress down, latency regresses up."""
    u = (unit or "").lower()
    if "ms" in u or "second" in u or u.endswith("s avg ttft"):
        return False
    return True        # tokens/s, capacity ratios, unit-less counts


def _rows_by_metric(doc):
    rows = doc.get("results", doc) if isinstance(doc, dict) else doc
    out = {}
    for r in rows:
        m = r.get("metric")
        if m:
            out[m] = r               # last write wins, like the bench
    return out


def _relative_change(baseline, fresh, better_up):
    """Signed relative regression: positive = got worse."""
    if baseline == 0:
        return 0.0 if fresh == 0 else float("inf")
    change = (fresh - baseline) / abs(baseline)
    return -change if better_up else change


def compare(baseline_doc, fresh_doc, tolerance=0.25, det_tolerance=0.0,
            allow_regress=()):
    """Compare two bench documents; returns a report dict.

    Only metrics present in BOTH documents are gated (a ``--only``
    fresh run re-measures one section; everything else is skipped and
    listed).  ``allow_regress`` entries are case-insensitive substrings
    matched against ``metric`` or ``metric::field``."""
    base = _rows_by_metric(baseline_doc)
    fresh = _rows_by_metric(fresh_doc)
    shared = sorted(set(base) & set(fresh))
    allow = [a.lower() for a in allow_regress]

    def _allowed(metric, field):
        probe = f"{metric}::{field}".lower()
        return any(a in probe for a in allow)

    findings, regressions, allowed = [], 0, 0
    compared = 0
    for metric in shared:
        b, f = base[metric], fresh[metric]
        checks = [("value", higher_is_better(b.get("unit")), tolerance)]
        for field, up in DETERMINISTIC_FIELDS.items():
            if field in b and field in f:
                checks.append((field, up, det_tolerance))
        for field, up, tol in checks:
            bv, fv = b.get(field), f.get(field)
            if not isinstance(bv, (int, float)) or \
                    not isinstance(fv, (int, float)):
                continue
            compared += 1
            worse = _relative_change(bv, fv, up)
            if worse <= tol:
                continue
            ok = _allowed(metric, field)
            findings.append({
                "metric": metric,
                "field": field,
                "baseline": bv,
                "fresh": fv,
                "regression_pct": round(worse * 100.0, 2),
                "tolerance_pct": round(tol * 100.0, 2),
                "direction": "higher_is_better" if up
                             else "lower_is_better",
                "allowed": ok,
            })
            if ok:
                allowed += 1
            else:
                regressions += 1
    return {
        "ok": regressions == 0,
        "compared_metrics": len(shared),
        "compared_values": compared,
        "skipped_baseline_only": sorted(set(base) - set(fresh)),
        "skipped_fresh_only": sorted(set(fresh) - set(base)),
        "regressions": regressions,
        "allowed_regressions": allowed,
        "findings": findings,
    }


def load(path):
    with open(path) as f:
        return json.load(f)


def check_bench(baseline_path, fresh_path, tolerance=0.25,
                det_tolerance=0.0, allow_regress=(), bench_file=None):
    """File-level entry point for the CLI/CI: returns the compare()
    report with the paths recorded.

    ``bench_file`` names an alternative committed baseline document
    (MULTICHIP_BENCH.json rides the same gate as DECODE_BENCH.json);
    when given it overrides ``baseline_path`` and is recorded in the
    report."""
    if bench_file:
        baseline_path = bench_file
    report = compare(load(baseline_path), load(fresh_path),
                     tolerance=tolerance, det_tolerance=det_tolerance,
                     allow_regress=allow_regress)
    report["baseline"] = str(baseline_path)
    report["fresh"] = str(fresh_path)
    if bench_file:
        report["bench_file"] = str(bench_file)
    return report


def render_text(report):
    lines = [
        f"check-bench: {report['compared_metrics']} shared metrics, "
        f"{report['compared_values']} values gated "
        f"({len(report.get('skipped_baseline_only', []))} baseline-only "
        "skipped)"]
    for f in report["findings"]:
        tag = "ALLOWED" if f["allowed"] else "REGRESSION"
        lines.append(
            f"  {tag}: {f['metric']} [{f['field']}] "
            f"{f['baseline']} -> {f['fresh']} "
            f"({f['regression_pct']:+.1f}% worse, tolerance "
            f"{f['tolerance_pct']:.0f}%)")
    lines.append("PASS" if report["ok"] else
                 f"FAIL: {report['regressions']} regression(s)")
    return "\n".join(lines) + "\n"
