"""Collective-comms ledger + mesh-aware telemetry (observability
phase 4).

The serving engine got its cost cards, memory ledger, and HBM roofline
in phase 3; this module gives the DISTRIBUTED stack the same treatment
— the measurement layer every scale-out PR (sharded serving, ring
prefill, MoE fleets) inherits.  Four pieces:

**Jaxpr comms walker.**  :func:`analyze_jaxpr` walks a (Closed)Jaxpr
(the PR 1 Program-doctor recursion: sub-jaxprs discovered generically
from eqn params) and aggregates every collective primitive —
``psum``/``pmax``/``pmin``, ``all_gather``, ``reduce_scatter``
(reported under its lax spelling ``psum_scatter``), ``all_to_all``,
``ppermute`` — by ``(op, axis)``, with operand dtypes/bytes and the
axis size read from the enclosing ``shard_map`` eqn's mesh.  ``scan``
bodies multiply counts by the trip count; ``while`` bodies count once
and set ``unbounded_loops`` (trip count is data-dependent).  A psum
over several axes at once records one call per axis.  Scope note:
only EXPLICIT collectives are jaxpr-visible — collectives GSPMD
inserts while partitioning a pjit/NamedSharding program exist only in
post-SPMD HLO, so pure-GSPMD programs honestly report zero here.

**Wire-byte model.**  Analytic per-device wire traffic of the
bandwidth-optimal ring algorithms, from the operand bytes ``B`` the
jaxpr records: all-reduce ``2(n-1)/n * B``, reduce-scatter/all-to-all
``(n-1)/n * B``, all-gather ``(n-1) * B_shard`` (== ``(n-1)/n`` of the
gathered array), ppermute ``B``.  ``n == 1`` is the eager identity
world: zero wire bytes.

**Interconnect roofline.**  A per-tier bandwidth datasheet table (the
peer of ``memory.py``'s 819 GB/s HBM number): v5e ICI is 1600 Gbps
(200 GB/s) per chip each direction, DCN ~25 GB/s per host; unlisted
backends (cpu in CI) reuse the memoized memcpy probe — virtual devices
exchange through host memory.  :func:`modeled_comms_seconds` turns a
report into modeled seconds/dispatch and :func:`publish_dispatch`
keeps a live modeled-comms vs wall-clock ratio gauge.

**Mesh telemetry + skew gauges.**  :func:`mesh_snapshot` renders the
live ``HybridCommunicateGroup`` (axes, dims, comm rank-lists) for the
``/debug/mesh`` endpoint and the ``mesh`` CLI mode;
:func:`mesh_meta` stamps the same summary into the chrome-trace
export.  :func:`publish_pipeline_schedule` publishes the pipeline
bubble ratio from the fleet schedules' own tick counts (gpipe
``T = M+S-1``, interleaved ``T = M+D-1``, 1f1b ``T = M+2(D-1)``;
bubble = ``(T-M)/T``) and :func:`observe_expert_load` the MoE
max/mean tokens-per-expert imbalance.

Metric families (ticked by both the walker's :meth:`CommsReport.publish`
and the eager wrappers in ``distributed/communication.py``):
``comms.collective_calls{op,axis}`` and ``comms.wire_bytes{op,axis}``.
"""

from __future__ import annotations

import math

from . import events as _events
from . import memory as _memory
from . import metrics as _metrics

__all__ = [
    "COLLECTIVE_OPS", "CommsReport", "analyze_jaxpr", "analyze_fn",
    "wire_bytes", "record_collective", "interconnect_bandwidth_gbs",
    "modeled_comms_seconds", "publish_dispatch", "mesh_snapshot",
    "mesh_meta", "mesh_json", "to_json", "publish_pipeline_schedule",
    "observe_expert_load",
]

# ------------------------------------------------------------- metrics
_CALLS = _metrics.counter(
    "comms.collective_calls",
    "collective ops recorded, by op and mesh axis (jaxpr walker "
    "publishes per trace; eager wrappers per call)")
_WIRE = _metrics.counter(
    "comms.wire_bytes",
    "modeled per-device ring-algorithm wire bytes, by op and mesh axis")
_MODELED_S = _metrics.gauge(
    "comms.modeled_seconds",
    "modeled wire seconds per dispatch of a program at datasheet "
    "interconnect bandwidth")
_RATIO = _metrics.gauge(
    "comms.compute_comms_ratio",
    "(dispatch wall seconds - modeled comms seconds) / modeled comms "
    "seconds; +Inf for a comms-free program")
_UTIL = _metrics.gauge(
    "comms.roofline_utilization",
    "modeled comms seconds / dispatch wall seconds — the share of the "
    "dispatch the wire would claim at datasheet bandwidth")
_BUBBLE = _metrics.gauge(
    "comms.pipeline_bubble_ratio",
    "idle fraction of the pipeline schedule: (ticks - microbatches) / "
    "ticks, from the schedule's own tick-count formula")
_TICKS = _metrics.gauge(
    "comms.pipeline_ticks",
    "schedule ticks per train_batch (gpipe M+S-1, interleaved M+D-1, "
    "1f1b M+2(D-1))")
_MOE_IMB = _metrics.gauge(
    "comms.moe_expert_load_imbalance",
    "max/mean tokens-per-expert of the last observed MoE dispatch "
    "(1.0 = perfectly balanced)")
_MOE_MAX = _metrics.gauge(
    "comms.moe_expert_tokens_max",
    "tokens routed to the most-loaded expert in the last observation")
_MOE_MEAN = _metrics.gauge(
    "comms.moe_expert_tokens_mean",
    "mean tokens per expert in the last observation")

# ------------------------------------------------- primitive taxonomy
#: jaxpr primitive name -> canonical op label.  lax.psum_scatter's
#: primitive prints as ``reduce_scatter``; the ledger uses the lax
#: (and reference ``c_reducescatter``-adjacent) spelling.
_PRIM_CANON = {
    "psum": "psum",
    "pmax": "pmax",
    "pmin": "pmin",
    "all_gather": "all_gather",
    "reduce_scatter": "psum_scatter",
    "psum_scatter": "psum_scatter",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
}

COLLECTIVE_OPS = ("psum", "pmax", "pmin", "all_gather", "psum_scatter",
                  "all_to_all", "ppermute")

#: ops whose ring algorithm is the all-reduce double pass
_ALLREDUCE_CLASS = {"psum", "pmax", "pmin"}


def wire_bytes(op, world_size, operand_bytes):
    """Modeled per-device wire bytes of ONE collective call: ``op`` over
    an axis of ``world_size`` ranks with ``operand_bytes`` per-device
    operand bytes (the shard each device holds going in).  Ring
    algorithms: all-reduce ``2(n-1)/n*B``; reduce-scatter/all-to-all
    ``(n-1)/n*B``; all-gather ``(n-1)*B`` of the SHARD (== ``(n-1)/n``
    of the gathered array); ppermute ``B``.  ``n <= 1`` — the eager
    identity world — is 0."""
    n = int(world_size or 0)
    b = float(operand_bytes or 0)
    if n <= 1 or b <= 0:
        return 0.0
    if op in _ALLREDUCE_CLASS:
        return 2.0 * (n - 1) / n * b
    if op in ("psum_scatter", "all_to_all"):
        return (n - 1) / n * b
    if op == "all_gather":
        return (n - 1) * b
    if op == "ppermute":
        return b
    return 0.0


def record_collective(op, axis, world_size=1, operand_bytes=0):
    """Tick the ``comms.*`` counter families for one collective call —
    the eager-path entry used by ``distributed/communication.py``
    wrappers (world-size-1 identity calls still count a call; their
    wire bytes are 0 by the model)."""
    canon = _PRIM_CANON.get(op, op)
    ax = axis if axis else "world"
    _CALLS.inc(1, op=canon, axis=ax)
    w = wire_bytes(canon, world_size, operand_bytes)
    if w:
        _WIRE.inc(w, op=canon, axis=ax)
    return w


# --------------------------------------------------------- the walker
class CommsReport:
    """Aggregated collective census of one program, by ``(op, axis)``.

    ``sites[(op, axis)]`` holds per-DISPATCH totals: ``calls``,
    ``operand_bytes``, modeled ``wire_bytes``, the ``axis_size`` the
    model used (None when no enclosing shard_map declared the axis),
    and the operand ``dtypes`` seen."""

    __slots__ = ("sites", "unbounded_loops", "unknown_axes")

    def __init__(self):
        self.sites = {}
        self.unbounded_loops = 0
        self.unknown_axes = set()

    def add(self, op, axis, calls, operand_bytes, axis_size, dtypes=()):
        key = (op, axis)
        site = self.sites.get(key)
        if site is None:
            site = self.sites[key] = {
                "op": op, "axis": axis, "calls": 0, "operand_bytes": 0.0,
                "wire_bytes": 0.0, "axis_size": axis_size,
                "dtypes": set()}
        site["calls"] += int(calls)
        site["operand_bytes"] += float(calls) * float(operand_bytes)
        if axis_size is None:
            self.unknown_axes.add(axis)
        else:
            site["axis_size"] = int(axis_size)
            site["wire_bytes"] += float(calls) * wire_bytes(
                op, axis_size, operand_bytes)
        site["dtypes"].update(dtypes)

    # ------------------------------------------------------- summaries
    def counts(self):
        """{(op, axis): calls} — the hand-derivable census tests gate."""
        return {k: v["calls"] for k, v in self.sites.items()}

    @property
    def total_calls(self):
        return sum(v["calls"] for v in self.sites.values())

    @property
    def total_wire_bytes(self):
        return sum(v["wire_bytes"] for v in self.sites.values())

    def calls_by_op(self):
        out = {op: 0 for op in COLLECTIVE_OPS}
        for (op, _), site in self.sites.items():
            out[op] = out.get(op, 0) + site["calls"]
        return out

    def rows(self):
        return [dict(site, dtypes=sorted(site["dtypes"]))
                for _, site in sorted(self.sites.items())]

    def to_json(self):
        return {
            "collective_calls": self.total_calls,
            "wire_bytes": round(self.total_wire_bytes, 1),
            "unbounded_loops": self.unbounded_loops,
            "unknown_axes": sorted(self.unknown_axes),
            "by_op_axis": self.rows(),
        }

    def publish(self):
        """Tick the process ``comms.*`` counters with this report's
        per-dispatch totals (called once per capture/trace, not per
        dispatch — the ledger counts traced programs' comms plans)."""
        for (op, axis), site in sorted(self.sites.items()):
            _CALLS.inc(site["calls"], op=op, axis=axis)
            if site["wire_bytes"]:
                _WIRE.inc(site["wire_bytes"], op=op, axis=axis)
        return self


def _doctor():
    # lazy: reuse the PR 1 Program-doctor helpers without importing the
    # analysis package (and its AST passes) at module-import time
    from ..analysis import graph_doctor

    return graph_doctor


def _aval_bytes(v):
    aval = getattr(v, "aval", None)
    try:
        return int(aval.size) * int(aval.dtype.itemsize)
    except Exception:
        return 0


def _aval_dtype(v):
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return str(dt) if dt is not None else None


def _mesh_axis_sizes(mesh):
    """{axis: size} from a shard_map eqn's mesh param (Mesh or
    AbstractMesh — both expose ``shape``)."""
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:
        return {}


def _walk(jaxpr, axis_sizes, mult, report, doctor):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        canon = _PRIM_CANON.get(name)
        if canon is not None:
            nbytes = sum(_aval_bytes(v) for v in eqn.invars)
            dtypes = {d for d in (_aval_dtype(v) for v in eqn.invars)
                      if d is not None}
            for ax in doctor._axis_names(eqn.params):
                report.add(canon, ax, mult, nbytes,
                           axis_sizes.get(ax), dtypes)
            continue
        sub_mult = mult
        sub_sizes = axis_sizes
        if name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1) or 1)
        elif name == "while":
            # trip count is data-dependent; count the body once, flag it
            report.unbounded_loops += 1
        elif "shard_map" in name:
            mesh = eqn.params.get("mesh")
            if mesh is not None:
                sub_sizes = dict(axis_sizes)
                sub_sizes.update(_mesh_axis_sizes(mesh))
        for sub in doctor._sub_jaxprs(eqn.params):
            _walk(sub, sub_sizes, sub_mult, report, doctor)


def analyze_jaxpr(closed_jaxpr, axis_sizes=None):
    """Walk a (Closed)Jaxpr and return its :class:`CommsReport`.

    ``axis_sizes`` seeds the axis-name -> size map for collectives not
    under any ``shard_map`` eqn in the jaxpr (e.g. a jaxpr traced
    *inside* the mapped region); shard_map eqns encountered during the
    walk contribute their own mesh's sizes."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    report = CommsReport()
    _walk(jaxpr, dict(axis_sizes or {}), 1, report, _doctor())
    return report


def analyze_fn(fn, *args, axis_sizes=None, **kwargs):
    """Trace ``fn(*args, **kwargs)`` with ``jax.make_jaxpr`` and walk
    the result — the one-call census for tests and benches."""
    import jax

    return analyze_jaxpr(jax.make_jaxpr(fn)(*args, **kwargs),
                         axis_sizes=axis_sizes)


# ------------------------------------------------ interconnect roofline
#: Published interconnect bandwidth per accelerator backend, GB/s per
#: chip (the peer of memory.py's 819 GB/s HBM row).  v5e ICI: 1600 Gbps
#: per chip each direction = 200 GB/s; DCN (multi-slice, per host NIC)
#: ~200 Gbps = 25 GB/s.  "axon" is the same part behind the tunneled
#: plugin.
_ICI_BW_TABLE = {"tpu": 200.0, "axon": 200.0}
_DCN_BW_TABLE = {"tpu": 25.0, "axon": 25.0}


def interconnect_bandwidth_gbs(backend, tier="ici"):
    """Interconnect bandwidth for ``backend`` in GB/s: datasheet table
    for known accelerators; unlisted backends (cpu in CI, where the
    virtual devices of --xla_force_host_platform_device_count exchange
    through host memory) reuse :func:`memory.backend_bandwidth_gbs`'s
    memoized memcpy probe, so the bench and the live gauge agree."""
    table = _ICI_BW_TABLE if tier == "ici" else _DCN_BW_TABLE
    if backend in table:
        return table[backend]
    return _memory.backend_bandwidth_gbs(backend)


def modeled_comms_seconds(report, backend, tier_by_axis=None):
    """Modeled wire seconds of ONE dispatch of a program: each site's
    wire bytes over its axis tier's datasheet bandwidth, summed (rings
    on distinct axes modeled sequentially — no overlap credit).
    ``tier_by_axis`` maps axis name -> "ici"/"dcn" (default: every
    axis on ici)."""
    tiers = tier_by_axis or {}
    total = 0.0
    for (_, axis), site in report.sites.items():
        bw = interconnect_bandwidth_gbs(backend, tiers.get(axis, "ici"))
        total += site["wire_bytes"] / (bw * 1e9)
    return total


def publish_dispatch(fn, key, report, wall_seconds, backend,
                     tier_by_axis=None):
    """Live compute-vs-comms gauges for one measured dispatch of a
    carded program: modeled comms seconds, the modeled share of the
    wall clock, and the compute:comms ratio.  Returns the modeled
    comms seconds."""
    comms_s = modeled_comms_seconds(report, backend,
                                    tier_by_axis=tier_by_axis)
    labels = dict(fn=fn, key=key)
    _MODELED_S.set(comms_s, **labels)
    if comms_s > 0:
        _RATIO.set((wall_seconds - comms_s) / comms_s, **labels)
    else:
        _RATIO.set(math.inf, **labels)
    if wall_seconds > 0:
        _UTIL.set(comms_s / wall_seconds, **labels)
    return comms_s


# --------------------------------------------------- mesh telemetry
def mesh_snapshot():
    """The live ``HybridCommunicateGroup`` as JSON: per-axis name/dim/
    comm rank-lists (the reference's per-axis NCCL communicators),
    mesh shape, device platform.  ``{"initialized": False}`` when no
    hybrid group exists — the endpoint must answer either way."""
    try:
        from ..distributed.topology import (get_hybrid_communicate_group,
                                            mesh_axis_name)
    except Exception:                # pragma: no cover - defensive
        return {"initialized": False}
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return {"initialized": False}
    topo = hcg.topology()
    axes = []
    for name in topo.get_hybrid_group_names():
        axes.append({
            "name": name,
            "mesh_axis": mesh_axis_name(name),
            "dim": topo.get_dim(name),
            "comm_lists": topo.get_comm_list(name),
        })
    mesh = hcg.mesh
    dev0 = mesh.devices.flat[0]
    return {
        "initialized": True,
        "world_size": hcg.nranks,
        "global_rank": hcg.get_global_rank(),
        "parallel_mode": hcg.get_parallel_mode(),
        "mesh_shape": _mesh_axis_sizes(mesh),
        "platform": str(getattr(dev0, "platform", "unknown")),
        "axes": axes,
    }


def mesh_meta():
    """Compact mesh summary for the chrome-trace metadata stamp (None
    when no hybrid group is live)."""
    snap = mesh_snapshot()
    if not snap.get("initialized"):
        return None
    return {"world_size": snap["world_size"],
            "mesh_shape": snap["mesh_shape"],
            "parallel_mode": snap["parallel_mode"]}


def to_json():
    """The comms ledger (``/debug/comms``): every ``comms.*`` family's
    current values plus the interconnect datasheet."""
    families = (
        "comms.collective_calls", "comms.wire_bytes",
        "comms.modeled_seconds", "comms.compute_comms_ratio",
        "comms.roofline_utilization", "comms.pipeline_bubble_ratio",
        "comms.pipeline_ticks", "comms.moe_expert_load_imbalance",
        "comms.moe_expert_tokens_max", "comms.moe_expert_tokens_mean",
    )
    reg = _metrics.default_registry()
    out = {"families": {}}
    for fam in families:
        m = reg.get(fam)
        if m is not None:
            out["families"][fam] = m.snapshot_values()
    calls = _CALLS.snapshot_values()
    wire = _WIRE.snapshot_values()
    out["collective_calls_total"] = sum(calls.values())
    out["wire_bytes_total"] = sum(wire.values())
    out["interconnect_gbs"] = {"ici": dict(_ICI_BW_TABLE),
                               "dcn": dict(_DCN_BW_TABLE)}
    return out


def mesh_json():
    """``/debug/mesh`` payload: the topology plus the comms ledger."""
    return {"mesh": mesh_snapshot(), "comms": to_json()}


# ------------------------------------------------------- skew gauges
#: tick-count formulas, mirroring the schedule builders in
#: fleet/meta_parallel/pipeline_parallel.py (gpipe line ~242,
#: interleaved ~337, 1f1b ~749); D = stages * virtual chunks
_SCHEDULE_TICKS = {
    "gpipe": lambda m, s, d: m + s - 1,
    "interleaved": lambda m, s, d: m + d - 1,
    "1f1b": lambda m, s, d: m + 2 * (d - 1),
}


def publish_pipeline_schedule(schedule, num_stages, num_micro,
                              virtual=1):
    """Pipeline-bubble skew gauge from the schedule's tick count: the
    fleet schedules run ``T`` ticks for ``M`` microbatches of useful
    work per stage, so ``(T - M) / T`` of the schedule is bubble.
    Returns the bubble ratio (0 for a 1-stage 'pipeline')."""
    s = max(1, int(num_stages))
    v = max(1, int(virtual))
    m = max(1, int(num_micro))
    d = s * v
    ticks_fn = _SCHEDULE_TICKS.get(schedule, _SCHEDULE_TICKS["gpipe"])
    ticks = int(ticks_fn(m, s, d))
    bubble = (ticks - m) / ticks if ticks > 0 else 0.0
    _TICKS.set(ticks, schedule=schedule)
    _BUBBLE.set(round(bubble, 6), schedule=schedule)
    _events.instant("comms.pipeline_schedule", cat="observability",
                    schedule=schedule, stages=s, virtual=v,
                    microbatches=m, ticks=ticks,
                    bubble_ratio=round(bubble, 4))
    return bubble


def observe_expert_load(tokens_per_expert, layer="moe"):
    """MoE expert-load skew gauge: max/mean tokens-per-expert of one
    observed dispatch (``MoELayer`` records ``tokens_per_expert`` each
    forward; call this with it OUTSIDE the traced region, where the
    values are concrete).  Returns the imbalance ratio (1.0 ==
    perfectly balanced), or None for an empty/all-dropped dispatch."""
    import numpy as np

    arr = np.asarray(getattr(tokens_per_expert, "_data",
                             tokens_per_expert), dtype=float).reshape(-1)
    if arr.size == 0:
        return None
    mean = float(arr.mean())
    mx = float(arr.max())
    if mean <= 0:
        return None
    imb = mx / mean
    _MOE_IMB.set(round(imb, 6), layer=layer)
    _MOE_MAX.set(mx, layer=layer)
    _MOE_MEAN.set(round(mean, 3), layer=layer)
    return imb
