"""Device-memory ledger + online roofline (observability phase 3).

Two answers this module owns:

**Where did the HBM go?**  :class:`MemoryLedger` holds one byte-
accounting callable per named component (the engine registers its paged
KV pool, its weight arrays, and its device-resident decode state) and
reconciles their sum against what JAX actually holds alive
(``jax.live_arrays()``).  ``snapshot()`` publishes the result as
``memory.*`` gauges:

* ``memory.accounted_bytes{ledger,component}`` — each component's own
  claim;
* ``memory.accounted_total_bytes`` / ``memory.live_bytes`` — the two
  sides of the reconciliation;
* ``memory.unaccounted_bytes`` — live minus accounted (rotary tables,
  scratch, anything nobody claims);
* ``memory.leak_delta_bytes`` — the leak detector: growth of the
  unaccounted residue since the baseline mark.  Pool-accounted bytes
  are allowed to grow (admission allocates blocks); bytes NOBODY
  accounts for growing monotonically is a leak signature.

Reconciliation walks every live array, so it runs on demand
(``Engine.stats()``, tests, dashboards) — not per decode step.

**How close to the roofline is decode running?**  The per-backend
bandwidth probe lives here (moved from benchmarks/bench_decode.py so
the live engine and the bench share one number): a datasheet table for
known accelerators, a one-shot 64 MiB memcpy probe otherwise.  The
engine combines a decode program card's bytes-accessed with its
dispatch wall time and publishes
``memory.roofline_utilization{engine,horizon}`` — the bench's
``roofline_pct`` column as a LIVE gauge.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from . import metrics as _metrics

_ACCT = _metrics.gauge(
    "memory.accounted_bytes",
    "device bytes each registered component claims to hold")
_ACCT_TOTAL = _metrics.gauge(
    "memory.accounted_total_bytes",
    "sum of all component-accounted device bytes")
_LIVE = _metrics.gauge(
    "memory.live_bytes",
    "total bytes of jax.live_arrays() at the last reconcile")
_UNACCT = _metrics.gauge(
    "memory.unaccounted_bytes",
    "live bytes no registered component accounts for")
_LEAK = _metrics.gauge(
    "memory.leak_delta_bytes",
    "growth of the unaccounted residue since the baseline mark")
_HOST_ACCT = _metrics.gauge(
    "memory.host_arena_bytes",
    "pinned host-RAM bytes each registered host component holds (the "
    "tiered-KV spill arena); deliberately OUTSIDE the device "
    "reconciliation — host numpy buffers never appear in "
    "jax.live_arrays(), so folding them into accounted_total_bytes "
    "would poison unaccounted/leak_delta")
_ROOFLINE = _metrics.gauge(
    "memory.roofline_utilization",
    "achieved bytes/s of the last decode dispatch / backend bandwidth")
_ACHIEVED = _metrics.gauge(
    "memory.achieved_bandwidth_gbs",
    "bytes-accessed of the last decode dispatch over its wall seconds")

#: Published HBM bandwidth per accelerator backend (GB/s).  v5e HBM2e
#: is the paper's serving chip; "axon" is the same part behind the
#: tunneled plugin.  Unlisted backends (cpu in CI) are measured once
#: per process by a memcpy probe instead of being skipped.
_HBM_BW_TABLE = {"tpu": 819.0, "axon": 819.0}
#: Host<->device transfer bandwidth per backend (GB/s) — the tiered-KV
#: swap path's roofline, NOT the HBM number above.  v5e attaches over
#: PCIe gen3 x16 (~16 GB/s per direction in practice); unlisted
#: backends (cpu in CI, where "upload" is a memcpy) fall through to
#: the same memcpy probe as the HBM path, keyed separately so the two
#: memoized figures never alias.
_HOST_BW_TABLE = {"tpu": 16.0, "axon": 16.0}
_BW_PROBED = {}
_BW_LOCK = threading.Lock()


def backend_bandwidth_gbs(backend):
    """Roofline bandwidth for ``backend`` in GB/s: the datasheet table
    when we have one, else a one-shot streaming-memcpy probe (64 MiB
    source, read+write counted, best of 4 passes — DRAM speed, not L3,
    at that footprint).  Memoized: the probe runs at most once per
    process so the live gauge and every bench section agree on the
    number."""
    if backend in _HBM_BW_TABLE:
        return _HBM_BW_TABLE[backend]
    return _memcpy_probe_gbs(backend)


def host_device_bandwidth_gbs(backend):
    """Host<->device transfer bandwidth for ``backend`` in GB/s — what
    a tiered-KV swap's upload seconds divide by (the swap-vs-recompute
    policy and bench crossover both normalize with this one number).
    Datasheet PCIe figure for known accelerators; on cpu backends a
    host->device "transfer" is a memcpy, so the memcpy probe IS the
    honest figure."""
    if backend in _HOST_BW_TABLE:
        return _HOST_BW_TABLE[backend]
    return _memcpy_probe_gbs(("host", backend))


def _memcpy_probe_gbs(key):
    with _BW_LOCK:
        if key not in _BW_PROBED:
            src = np.ones(1 << 26, np.uint8)          # 64 MiB
            dst = np.empty_like(src)
            np.copyto(dst, src)                       # fault pages in
            best = None
            for _ in range(4):
                t0 = time.perf_counter()
                np.copyto(dst, src)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            _BW_PROBED[key] = round(2.0 * src.nbytes / best / 1e9, 1)
        return _BW_PROBED[key]


def live_device_bytes():
    """Total bytes of every live jax array in the process (0 when the
    runtime doesn't expose live_arrays)."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:                # pragma: no cover - defensive
        return 0
    total = 0
    for a in arrays:
        try:
            total += int(a.nbytes)
        except Exception:            # deleted/donated buffers
            continue
    return total


def publish_roofline(engine, horizon, bytes_accessed, wall_seconds,
                     backend):
    """One decode dispatch's achieved-vs-roofline utilization as live
    gauges (called by the engine after each non-compiling dispatch)."""
    if not bytes_accessed or wall_seconds <= 0:
        return None
    achieved = bytes_accessed / wall_seconds / 1e9
    util = achieved / backend_bandwidth_gbs(backend)
    _ACHIEVED.set(round(achieved, 4), engine=engine, horizon=horizon)
    _ROOFLINE.set(round(util, 6), engine=engine, horizon=horizon)
    return util


class MemoryLedger:
    """Named byte-accounting components reconciled against
    ``jax.live_arrays()``.

    Components are zero-arg callables returning their current device
    bytes; they are polled at ``snapshot()`` time.  The ledger never
    holds device arrays itself — callables typically close over the
    pools they account, and the engine owns the ledger, so its
    lifetime is the engine's."""

    def __init__(self, name=""):
        self.name = name
        self._lock = threading.Lock()
        self._components = {}
        self._host_components = {}
        self._baseline_unaccounted = None

    def register(self, component, fn):
        if not callable(fn):
            raise TypeError("component accounting fn must be callable")
        with self._lock:
            self._components[component] = fn
        return self

    def register_host(self, component, fn):
        """Register a HOST-memory component (pinned numpy arenas — the
        tiered-KV spill tier).  Host bytes are published as
        ``memory.host_arena_bytes`` and reported in the snapshot, but
        NEVER summed into the device reconciliation: they are invisible
        to ``jax.live_arrays()``, so counting them as accounted would
        drive ``unaccounted_bytes`` negative and break the
        ``leak_delta_bytes`` exactness the leak detector rests on."""
        if not callable(fn):
            raise TypeError("component accounting fn must be callable")
        with self._lock:
            self._host_components[component] = fn
        return self

    def unregister(self, component):
        with self._lock:
            self._components.pop(component, None)

    def components(self):
        with self._lock:
            return list(self._components)

    def account(self):
        """Poll every component: {component: bytes} (a component that
        raises reports 0 rather than poisoning the snapshot)."""
        with self._lock:
            items = list(self._components.items())
        out = {}
        for name, fn in items:
            try:
                out[name] = int(fn())
            except Exception:        # pragma: no cover - defensive
                out[name] = 0
        return out

    def account_host(self):
        """Poll every host component: {component: bytes}."""
        with self._lock:
            items = list(self._host_components.items())
        out = {}
        for name, fn in items:
            try:
                out[name] = int(fn())
            except Exception:        # pragma: no cover - defensive
                out[name] = 0
        return out

    def mark_baseline(self):
        """Re-anchor the leak detector at the current residue (called
        automatically by the first snapshot)."""
        acct = self.account()
        self._baseline_unaccounted = (live_device_bytes()
                                      - sum(acct.values()))
        return self._baseline_unaccounted

    def snapshot(self):
        """Reconcile + publish the ``memory.*`` gauges; returns the
        ledger state as a JSON-able dict."""
        acct = self.account()
        accounted = sum(acct.values())
        live = live_device_bytes()
        unaccounted = live - accounted
        if self._baseline_unaccounted is None:
            self._baseline_unaccounted = unaccounted
        leak = unaccounted - self._baseline_unaccounted
        labels = dict(ledger=self.name)
        for comp, b in acct.items():
            _ACCT.set(b, component=comp, **labels)
        _ACCT_TOTAL.set(accounted, **labels)
        _LIVE.set(live, **labels)
        _UNACCT.set(unaccounted, **labels)
        _LEAK.set(leak, **labels)
        host = self.account_host()
        for comp, b in host.items():
            _HOST_ACCT.set(b, component=comp, **labels)
        out = {
            "ledger": self.name,
            "components": acct,
            "accounted_total_bytes": accounted,
            "live_bytes": live,
            "unaccounted_bytes": unaccounted,
            "leak_delta_bytes": leak,
        }
        if host:
            # reported alongside, summed into NOTHING above: see
            # register_host for why host bytes stay out of the device
            # reconciliation
            out["host_components"] = host
            out["host_total_bytes"] = sum(host.values())
        return out
