"""SLO tracking: declared objectives, rolling compliance, multi-window
burn rate.

An :class:`Objective` declares one service-level objective in the
classic SRE shape — "for ``target`` of observations, ``value`` must be
``<= threshold``" (TTFT p95 < 500 ms is ``threshold=0.5,
target=0.95``; abort rate < 5 % is 0/1 error observations with
``threshold=0.5, target=0.95``).  Every observation lands in TWO
rolling windows, a fast one and a slow one, each backed by the SAME
bounded-reservoir machinery the metrics registry's Histograms use —
windows are sized in **observations (steps), not wall-clock seconds**,
so compliance math is deterministic under test (no sleeping, no clock
injection).

Per window the tracker computes:

* **compliance** — fraction of the window's observations that met the
  threshold (1.0 while the window is empty: no evidence of breach —
  but snapshots carry a per-window ``idle`` flag and a per-objective
  ``idle`` so consumers can tell "healthy" from "unmeasured"; the
  fleet attainment curves must not credit idle replicas);
* **burn rate** — ``(1 - compliance) / (1 - target)``: how many times
  faster than budget the error budget is burning (1.0 = exactly on
  budget, 20 = a full fast-window outage at target 0.95).

Breach detection is the standard multi-window AND: an objective is
unhealthy while BOTH windows burn above ``burn_threshold`` — the fast
window makes detection quick, the slow window keeps one bad step from
flapping, and recovery is fast because the fast window forgives as soon
as it refills with good observations.

:class:`SLOTracker` owns a set of objectives, publishes each as typed
gauges (``slo.compliance`` / ``slo.burn_rate`` per (objective, window),
``slo.objective_healthy`` per objective, and one overall
``slo.healthy``) and snapshots as JSON for ``/debug/slo``.  The overall
``slo_healthy`` signal is what the serving gateway will consume for
admission/shedding; today it drives the telemetry server's ``/readyz``.
"""

from __future__ import annotations

import threading

from .metrics import Histogram, Registry, default_registry

#: default window sizes, in observations ("fast 1m / slow 10m" at one
#: observation per second — but steps, so tests are deterministic)
DEFAULT_FAST_WINDOW = 64
DEFAULT_SLOW_WINDOW = 640

WINDOWS = ("fast", "slow")


class Objective:
    """One declared objective over a pair of step-sized windows."""

    def __init__(self, name, threshold, target=0.95,
                 fast_window=DEFAULT_FAST_WINDOW,
                 slow_window=DEFAULT_SLOW_WINDOW,
                 burn_threshold=1.0, unit="s", help=""):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if int(fast_window) < 1 or int(slow_window) < int(fast_window):
            raise ValueError("need slow_window >= fast_window >= 1")
        self.name = name
        self.threshold = float(threshold)
        self.target = float(target)
        self.burn_threshold = float(burn_threshold)
        self.unit = unit
        self.help = help
        # the rolling windows ARE histogram reservoirs: a private
        # registry keeps them off the process-wide exposition (the
        # tracker publishes derived gauges instead), while compliance
        # reads the same bounded ``samples`` deque Histogram percentiles
        # use
        self._reg = Registry()
        self._win = {
            "fast": Histogram(f"slo.window.{name}.fast",
                              reservoir=int(fast_window),
                              registry=self._reg),
            "slow": Histogram(f"slo.window.{name}.slow",
                              reservoir=int(slow_window),
                              registry=self._reg),
        }
        self._lock = threading.Lock()
        self.observations = 0
        self.breaches = 0

    def observe(self, value):
        """Record one observation into both windows."""
        value = float(value)
        with self._lock:
            self.observations += 1
            if value > self.threshold:
                self.breaches += 1
        for h in self._win.values():
            h.observe(value)

    def _samples(self, window):
        slot = self._win[window]._values.get(())
        return list(slot.samples) if slot is not None else []

    def window_size(self, window):
        return self._win[window].reservoir

    def compliance(self, window="fast"):
        """Fraction of the window's observations within threshold
        (1.0 while empty — an idle service is not in breach)."""
        samples = self._samples(window)
        if not samples:
            return 1.0
        good = sum(1 for v in samples if v <= self.threshold)
        return good / len(samples)

    def burn_rate(self, window="fast"):
        """Error-budget burn multiple: 1.0 = consuming exactly the
        budget ``1 - target`` allows, >1 = burning faster."""
        return (1.0 - self.compliance(window)) / (1.0 - self.target)

    @property
    def healthy(self):
        """Multi-window breach rule: unhealthy only while BOTH windows
        burn above ``burn_threshold``."""
        return not (self.burn_rate("fast") > self.burn_threshold
                    and self.burn_rate("slow") > self.burn_threshold)

    @property
    def idle(self):
        """True while BOTH windows are empty: compliance/burn report
        the vacuous defaults with zero evidence behind them.  A
        zero-traffic replica is "compliant" only in the sense that it
        was never measured — consumers building attainment curves must
        check this flag instead of crediting the 1.0."""
        return all(not self._samples(w) for w in WINDOWS)

    def snapshot(self):
        out = {
            "threshold": self.threshold,
            "target": self.target,
            "burn_threshold": self.burn_threshold,
            "unit": self.unit,
            "observations": self.observations,
            "breaches": self.breaches,
            "healthy": self.healthy,
            "idle": self.idle,
        }
        for w in WINDOWS:
            samples = len(self._samples(w))
            out[w] = {
                "window_steps": self.window_size(w),
                "samples": samples,
                # an empty window's compliance=1.0 is vacuous, not
                # evidence of health — the flag keeps the distinction
                "idle": samples == 0,
                "compliance": round(self.compliance(w), 6),
                "burn_rate": round(self.burn_rate(w), 6),
            }
        return out


class SLOTracker:
    """A named set of objectives plus their published gauges.

    ``tracker`` labels every gauge so two engines (or an engine and a
    gateway) in one process stay distinguishable.  Gauges refresh on
    every ``observe()`` — observation rate is request retirement rate,
    so publish cost is negligible."""

    def __init__(self, name="default", registry=None):
        self.name = name
        self._objectives = {}
        reg = default_registry() if registry is None else registry
        self._g_compliance = reg.gauge(
            "slo.compliance",
            "rolling fraction of observations within objective threshold")
        self._g_burn = reg.gauge(
            "slo.burn_rate",
            "error-budget burn multiple per (objective, window)")
        self._g_obj_healthy = reg.gauge(
            "slo.objective_healthy",
            "1 while the objective's multi-window burn rule holds")
        self._g_healthy = reg.gauge(
            "slo.healthy",
            "1 while every declared objective is healthy (readiness "
            "signal for admission/shedding)")
        self._publish_overall()

    # ------------------------------------------------------------ declare
    def declare(self, name, threshold, **kwargs):
        """Declare (or replace) an objective; returns it."""
        obj = Objective(name, threshold, **kwargs)
        self._objectives[name] = obj
        self._publish(obj)
        return obj

    def objective(self, name):
        return self._objectives.get(name)

    def objectives(self):
        return dict(self._objectives)

    def __len__(self):
        return len(self._objectives)

    # ------------------------------------------------------------ observe
    def observe(self, name, value):
        """Record one observation against a declared objective (unknown
        names are ignored — instrumentation points fire whether or not
        an operator declared an objective for them)."""
        obj = self._objectives.get(name)
        if obj is None:
            return
        obj.observe(value)
        self._publish(obj)

    def _publish(self, obj):
        for w in WINDOWS:
            self._g_compliance.set(obj.compliance(w), tracker=self.name,
                                   objective=obj.name, window=w)
            self._g_burn.set(obj.burn_rate(w), tracker=self.name,
                             objective=obj.name, window=w)
        self._g_obj_healthy.set(int(obj.healthy), tracker=self.name,
                                objective=obj.name)
        self._publish_overall()

    def _publish_overall(self):
        self._g_healthy.set(int(self.healthy), tracker=self.name)

    # ------------------------------------------------------------ queries
    @property
    def healthy(self):
        """The overall readiness signal: every objective healthy (a
        tracker with no objectives is vacuously healthy)."""
        return all(o.healthy for o in self._objectives.values())

    @property
    def idle(self):
        """True while every declared objective is idle (or none are
        declared): the tracker's ``healthy`` is vacuous — nothing was
        measured.  The fleet harness uses this to keep zero-traffic
        replicas out of attainment credit."""
        return all(o.idle for o in self._objectives.values())

    def snapshot(self):
        return {
            "tracker": self.name,
            "healthy": self.healthy,
            "idle": self.idle,
            "objectives": {n: o.snapshot()
                           for n, o in sorted(self._objectives.items())},
        }
