"""``span(name, **labels)`` — one context manager, three sinks.

Entering a span simultaneously:

1. opens a ``jax.profiler.TraceAnnotation`` so the span shows up inside
   the XLA device trace (TensorBoard / Perfetto);
2. appends matching begin/end events to the host timeline
   (``observability.events``), nesting-aware via a per-thread depth;
3. on exit, observes the span's wall seconds into the
   ``span.seconds`` histogram labeled by span name (+ user labels).

This is the single instrumentation idiom the instrumented subsystems
(jit compile, serving requests, checkpoint saves) build on.
"""

from __future__ import annotations

import threading
import time

from . import events as _events
from . import metrics as _metrics

_tls = threading.local()

#: one histogram family for every span, labeled by name
SPAN_SECONDS = _metrics.histogram(
    "span.seconds", "wall seconds per observability span, by span name")


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_span():
    """Name of the innermost open span on this thread (None outside)."""
    s = _stack()
    return s[-1] if s else None


def span_depth():
    return len(_stack())


class span:
    """Context manager; also usable as a decorator-free timer via the
    ``elapsed`` attribute after exit."""

    def __init__(self, name, cat="host", event_args=None, **labels):
        """``labels`` key both the timeline events and the histogram —
        keep them LOW-CARDINALITY (a function name, a phase). Per-call
        detail (a file path, a request id) goes in ``event_args``, which
        reaches only the bounded event ring."""
        self.name = name
        self.cat = cat
        self.labels = labels
        self.event_args = dict(event_args) if event_args else {}
        self.elapsed = None
        self._t0 = None
        self._ann = None

    def __enter__(self):
        stack = _stack()
        try:
            import jax

            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:   # headless/stub jax: host timeline still works
            self._ann = None
        self._t0 = time.perf_counter()
        _events.record(self.name, phase=_events.BEGIN, cat=self.cat,
                       args=dict(self.labels, depth=len(stack),
                                 **self.event_args))
        stack.append(self.name)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = _stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self.elapsed = time.perf_counter() - self._t0
        try:
            _events.record(self.name, phase=_events.END, cat=self.cat,
                           args=dict(self.labels, depth=len(stack),
                                     seconds=round(self.elapsed, 9),
                                     error=exc_type.__name__ if exc_type
                                     else None, **self.event_args))
        finally:
            # the span must ALWAYS end: close the device annotation and
            # observe the histogram even if the event ring raised.  A
            # raising body tags the observation error=1 so error and
            # success latencies stay separable.
            if self._ann is not None:
                try:
                    self._ann.__exit__(exc_type, exc, tb)
                except Exception:
                    pass
            hist_labels = dict(self.labels)
            if exc_type is not None:
                hist_labels["error"] = 1
            SPAN_SECONDS.observe(self.elapsed, name=self.name,
                                 **hist_labels)
        return False
