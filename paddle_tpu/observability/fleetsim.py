"""Discrete-event fleet capacity simulator: the same workload trace,
stepped through a MODEL of the fleet instead of the fleet itself.

The fleet observatory's second half (observability phase 5).  Given a
:class:`~paddle_tpu.observability.loadgen.WorkloadTrace`, the
simulator answers the capacity question — "how many replicas for this
traffic at this SLO" — as a computable curve, in milliseconds instead
of a load test:

* **service times** come from a :class:`ServiceModel` — per-token
  prefill and decode seconds plus a per-request overhead — built one
  of three ways: analytically from the ProgramCard registry's
  FLOPs/bytes against a backend bandwidth/FLOPs datasheet (reusing
  :func:`~paddle_tpu.observability.memory.backend_bandwidth_gbs`),
  calibrated from a live replay report
  (:meth:`ServiceModel.from_replay` — the honest path on the CPU
  proxy, where rooflines do not bind), or given directly;
* **the fleet model** mirrors the serving stack's admission shape:
  prefix-population affinity routing (a stable hash, standing in for
  the router's rendezvous hash), per-replica slot pools, the
  scheduler's priority overtake BOUND (``window * (1 + gap)`` bypasses
  per victim, unbounded against offline batch-lane victims), queue
  deadlines, a per-replica radix-cache model (first request of a
  population pays full prefill, later ones pay the suffix), and
  client abort storms;
* **everything is deterministic** — no wall clock, no randomness; the
  event heap is keyed ``(time, sequence)`` so replays of the same
  trace produce identical timelines, and the 3-request micro-trace in
  the tests is checked against a hand-computed timeline exactly.

:func:`simulate` rolls its per-request records through the SAME
``loadgen.summarize`` the live replay uses, so
:func:`calibration_report` compares sim vs live like with like:
replica-count ordering must match exactly and attainment must agree
within a stated tolerance — the FLEET_BENCH row check-bench gates.
:func:`fleet_report` is the CLI ``fleet`` mode's engine: attainment-
vs-replica-count curves for named workload shapes in one invocation.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
from dataclasses import dataclass

from . import memory as _memory
from . import profiling as _profiling
from .loadgen import SHAPES, SLOSpec, generate, summarize

#: per-chip sustained FLOP/s datasheet for backends memory.py's
#: bandwidth table knows; unlisted backends (the CPU proxy) fall back
#: to a modest sustained rate so analytic models stay finite —
#: calibrate from a live replay for honest CPU numbers
_FLOPS_TABLE = {"tpu": 1.97e14, "axon": 1.97e14}
_FALLBACK_FLOPS = 5e10


@dataclass(frozen=True)
class ServiceModel:
    """Per-phase service-time model of one replica."""

    prefill_s_per_token: float = 2e-4
    decode_s_per_token: float = 2e-3
    #: per-request admission overhead (routing + submit hop)
    overhead_s: float = 1e-3

    def to_json(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_program_cards(cls, backend=None, registry=None,
                           overhead_s=1e-3):
        """Analytic model from the ProgramCard registry: each card's
        service time is its roofline ``max(flops/FLOP-rate,
        bytes/bandwidth)`` against the backend datasheet; per-token
        times average over the cards' dispatch-weighted token volume.
        Falls back to the defaults when no serving cards exist."""
        reg = registry if registry is not None \
            else _profiling.default_registry()
        cards = reg.cards()
        backend = backend or (cards[0].backend if cards else "cpu")
        bw = _memory.backend_bandwidth_gbs(backend) * 1e9
        flops_rate = _FLOPS_TABLE.get(backend, _FALLBACK_FLOPS)

        def _per_token(fn, tokens_of):
            t_sum = tok_sum = 0.0
            for c in cards:
                if c.fn != fn:
                    continue
                toks = tokens_of(c)
                if toks <= 0:
                    continue
                svc = max(float(c.flops) / flops_rate,
                          float(c.bytes_accessed) / bw)
                n = max(1, int(getattr(c, "dispatches", 1)))
                t_sum += svc * n
                tok_sum += toks * n
            return t_sum / tok_sum if tok_sum else None

        def _prefill_tokens(c):
            meta = c.meta or {}
            return (int(meta.get("lanes", 0) or 0)
                    * int(meta.get("bucket", 0) or 0))

        def _decode_tokens(c):
            meta = c.meta or {}
            return (int(meta.get("horizon", 0) or 0)
                    * int(meta.get("nb", meta.get("lanes", 0)) or 0))

        d = cls()
        pre = _per_token("serving.prefill", _prefill_tokens)
        dec = _per_token("serving.decode", _decode_tokens)
        return cls(
            prefill_s_per_token=(pre if pre is not None
                                 else d.prefill_s_per_token),
            decode_s_per_token=(dec if dec is not None
                                else d.decode_s_per_token),
            overhead_s=overhead_s)

    @classmethod
    def from_replay(cls, report):
        """Calibrate from a live replay report (``loadgen.replay``):
        decode seconds-per-token is the median observed TPOT, prefill
        seconds-per-token is the median (TTFT - queue wait) over the
        tokens each prefill actually computed (prompt minus prefix
        hits)."""
        pre, dec = [], []
        for r in report.get("records", []):
            if not r.get("completed"):
                continue
            if r.get("tpot_s") is not None:
                dec.append(r["tpot_s"])
            if (r.get("ttft_s") is not None
                    and r.get("queue_s") is not None):
                tokens = max(1, (r.get("prompt_tokens", 1)
                                 - r.get("prefix_hit_tokens", 0)))
                pre.append(max(0.0, r["ttft_s"] - r["queue_s"])
                           / tokens)

        def _median(vals, default):
            if not vals:
                return default
            vals = sorted(vals)
            return vals[len(vals) // 2]

        d = cls()
        return cls(
            prefill_s_per_token=_median(pre, d.prefill_s_per_token),
            decode_s_per_token=_median(dec, d.decode_s_per_token),
            overhead_s=d.overhead_s)


# ----------------------------------------------------------------- the sim
def _affine_replica(prefix_pop, n_replicas):
    """Stable population -> replica map (stands in for the router's
    rendezvous hash; any deterministic uniform map preserves the
    property that matters — same population, same replica)."""
    h = hashlib.blake2b(str(int(prefix_pop)).encode(),
                        digest_size=4).digest()
    return int.from_bytes(h, "big") % max(1, int(n_replicas))


class _SimReq:
    __slots__ = ("req", "t_arrive", "bypassed")

    def __init__(self, req, t_arrive):
        self.req = req
        self.t_arrive = t_arrive
        self.bypassed = 0

    @property
    def priority(self):
        return self.req.priority


def _overtake_cap(victim, overtaker, window):
    """The scheduler's overtake bound, batch-lane exemption included:
    a batch victim (priority < 0) may be passed by interactive traffic
    without bound; otherwise ``window * (1 + priority gap)``."""
    if victim.priority < 0 <= overtaker.priority:
        return float("inf")
    gap = max(0, int(overtaker.priority) - int(victim.priority))
    return window * (1 + gap)


def _pick_next(queue, window):
    """Pop the next admissible request: the highest-priority candidate
    whose every skipped-over victim still has overtake budget, FIFO
    within a priority.  Charges one bypass to each passed victim —
    the same budget discipline ``Scheduler.promote`` enforces."""
    if not queue:
        return None
    best = 0
    for i in range(1, len(queue)):
        r = queue[i]
        if r.priority <= queue[best].priority:
            continue
        if all(v.bypassed < _overtake_cap(v, r, window)
               for v in queue[:i]):
            best = i
    for v in queue[:best]:
        v.bypassed += 1
    return queue.pop(best)


class _Replica:
    __slots__ = ("free_slots", "queue", "cached_pops")

    def __init__(self, num_slots):
        self.free_slots = int(num_slots)
        self.queue = []
        self.cached_pops = set()

    @property
    def load(self):
        return len(self.queue)


def simulate(trace, n_replicas, model=None, *, speed=1.0, num_slots=4,
             reorder_window=8, max_queue=64, slo=None):
    """Step one trace through a fleet of ``n_replicas`` modeled
    replicas; returns the same report shape ``loadgen.replay``
    produces (``summarize`` rollup + ``records``), so the two are
    directly comparable.  ``speed`` compresses virtual arrival times
    exactly like replay's client threads, so calibration compares the
    same timeline."""
    model = model or ServiceModel()
    slo = slo or SLOSpec()
    speed = float(speed)
    if speed <= 0:
        raise ValueError("speed must be > 0")
    replicas = [_Replica(num_slots) for _ in range(int(n_replicas))]
    records = []
    heap = []
    seq = 0
    for req in trace.requests:
        heapq.heappush(heap, (req.t_submit / speed, seq, "arrive", req,
                              None))
        seq += 1

    def _admit(rep, now):
        nonlocal seq
        while rep.free_slots > 0 and rep.queue:
            sr = _pick_next(rep.queue, reorder_window)
            req = sr.req
            queue_s = now - sr.t_arrive
            deadline = (req.deadline_s / speed
                        if req.deadline_s is not None else None)
            if deadline is not None and queue_s > deadline:
                records.append(_record(req, queue_s=None,
                                       deadline_expired=True,
                                       aborted=True))
                continue
            hit = (req.prefix_len
                   if req.prefix_pop in rep.cached_pops else 0)
            rep.cached_pops.add(req.prefix_pop)
            prefill = (model.overhead_s
                       + (req.prompt_len - hit)
                       * model.prefill_s_per_token)
            t_first = now + prefill
            decode = (req.max_new_tokens - 1) * model.decode_s_per_token
            t_done = t_first + decode
            tokens = req.max_new_tokens
            aborted = False
            if req.abort_after_s is not None:
                t_abort = sr.t_arrive + req.abort_after_s / speed
                if t_abort < t_done:
                    aborted = True
                    tokens = (0 if t_abort < t_first else 1 + int(
                        (t_abort - t_first)
                        / model.decode_s_per_token))
                    t_done = max(t_abort, now)
            ttft = (t_first - sr.t_arrive) if tokens > 0 else None
            rec = _record(
                req, queue_s=round(queue_s, 9),
                ttft_s=round(ttft, 9) if ttft is not None else None,
                tpot_s=(model.decode_s_per_token
                        if tokens > 1 else None),
                tokens=tokens, prefix_hit_tokens=hit,
                aborted=aborted, completed=not aborted)
            records.append(rec)
            rep.free_slots -= 1
            heapq.heappush(heap, (t_done, seq, "finish", None, rep))
            seq += 1

    while heap:
        now, _, kind, req, rep = heapq.heappop(heap)
        if kind == "arrive":
            target = replicas[_affine_replica(req.prefix_pop,
                                              len(replicas))]
            if target.load >= max_queue:
                target = min(replicas, key=lambda r: (r.load,
                                                      -r.free_slots))
            if target.load >= max_queue:
                records.append(_record(req, shed=True))
                continue
            target.queue.append(_SimReq(req, now))
            _admit(target, now)
        else:
            rep.free_slots += 1
            _admit(rep, now)

    report = summarize(records, slo=slo)
    report["records"] = records
    report["replicas"] = int(n_replicas)
    report["speed"] = speed
    report["trace_digest"] = trace.digest()
    report["service_model"] = model.to_json()
    return report


def _record(req, *, queue_s=None, ttft_s=None, tpot_s=None, tokens=0,
            prefix_hit_tokens=0, completed=False, shed=False,
            aborted=False, deadline_expired=False):
    return {"index": req.index, "tenant": req.tenant, "tier": req.tier,
            "priority": req.priority, "prompt_tokens": req.prompt_len,
            "tokens": int(tokens),
            "prefix_hit_tokens": int(prefix_hit_tokens),
            "completed": completed, "shed": shed, "aborted": aborted,
            "deadline_expired": deadline_expired, "queue_s": queue_s,
            "ttft_s": ttft_s, "tpot_s": tpot_s}


# ----------------------------------------------------------- curves + calib
def attainment_curve(trace, replica_counts, model=None, **sim_kw):
    """SLO attainment at each replica count — the "how many chips for
    this traffic" curve."""
    curve = []
    for n in replica_counts:
        rep = simulate(trace, n, model, **sim_kw)
        curve.append({
            "replicas": int(n),
            "attainment": rep["attainment"],
            "shed": rep["shed"],
            "completed": rep["completed"],
            "tokens_total": rep["tokens_total"],
            "p95_ttft_s": rep["phase_latency"]["ttft_s"]["p95"],
            "per_tier_attainment": {
                t: g["attainment"]
                for t, g in rep["per_tier"].items()},
        })
    return curve


def calibration_report(trace, live_reports, model, *, speed,
                       tolerance=0.15, tie_eps=0.05, **sim_kw):
    """Sim-vs-live agreement on the CPU proxy: for each replica count
    with a live replay report, run the simulator on the same trace at
    the same speed and compare SLO attainment.  Gated claims: the
    ORDERING of replica counts by attainment must match, and the worst
    absolute attainment error must stay within ``tolerance``.

    Ordering is gated tie-aware: two replica counts whose live
    attainments sit within ``tie_eps`` are indistinguishable at live
    measurement noise (one stray scheduler hiccup moves one request
    across the threshold), so the gate fails only on a STRICT
    disagreement — a pair the live replay separates by more than
    ``tie_eps`` that the sim orders the other way (or vice versa).
    ``ordering_exact`` (sorted orders identical, ties broken by
    replica count) is still reported for the curious."""
    rows = []
    for n in sorted(live_reports):
        live = live_reports[n]
        sim = simulate(trace, n, model, speed=speed, **sim_kw)
        rows.append({"replicas": int(n),
                     "live_attainment": live["attainment"],
                     "sim_attainment": sim["attainment"],
                     "abs_err": round(abs(live["attainment"]
                                          - sim["attainment"]), 6)})
    order_live = [r["replicas"] for r in
                  sorted(rows, key=lambda r: (r["live_attainment"],
                                              r["replicas"]))]
    order_sim = [r["replicas"] for r in
                 sorted(rows, key=lambda r: (r["sim_attainment"],
                                             r["replicas"]))]
    eps = float(tie_eps)
    consistent = True
    for a in rows:
        for b in rows:
            live_says = a["live_attainment"] < b["live_attainment"] - eps
            sim_says = a["sim_attainment"] > b["sim_attainment"] + eps
            if live_says and sim_says:
                consistent = False
    max_err = max((r["abs_err"] for r in rows), default=0.0)
    ordering_exact = order_live == order_sim
    return {"rows": rows, "ordering_exact": ordering_exact,
            "ordering_consistent": consistent,
            "tie_eps": eps,
            "max_abs_err": round(max_err, 6),
            "tolerance": float(tolerance),
            "ok": consistent and max_err <= float(tolerance)}


# -------------------------------------------------------------- CPU proxy
def build_cpu_proxy_gateway(n_replicas, seed=0, num_slots=4,
                            max_seq_len=64, max_horizon=1,
                            model_id="fleet-proxy"):
    """A started live gateway over ``n_replicas`` tiny CPU engines
    with IDENTICAL weights (same init seed) — the live half of the
    calibration loop.  Caller owns shutdown().

    The engines run with ``ragged_attention=False`` and (by default)
    ``max_horizon=1``: the ragged path's block-table width ``nb``
    re-buckets as live sequences deepen and the adaptive horizon
    policy's picks depend on queue depth, so a measured replay that
    reaches a composition the warmup passes never hit pays a mid-run
    decode compile that stalls every in-flight request — pinning both
    collapses the decode program space to ONE program per engine so
    warmup coverage is complete.  (Numerics are bitwise-identical
    either way; only bytes-read and dispatch cadence change, which is
    exactly what ``ServiceModel.from_replay`` measures.)"""
    import paddle_tpu as paddle
    from ..models import GPTConfig, GPTForCausalLM
    from ..serving import Engine, EngineConfig
    from ..serving.gateway import Gateway, GatewayConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=64,
                    intermediate_size=128, num_hidden_layers=2,
                    num_attention_heads=4,
                    max_position_embeddings=max_seq_len)
    engines = []
    for _ in range(int(n_replicas)):
        paddle.seed(seed)
        m = GPTForCausalLM(cfg)
        m.eval()
        engines.append(Engine(
            m, EngineConfig(num_slots=num_slots,
                            max_seq_len=max_seq_len,
                            max_horizon=max_horizon,
                            ragged_attention=False),
            register_profiler=False))
    return Gateway(engines,
                   GatewayConfig(model_id=model_id)).start()


def warm_gateway(gw, trace, speed=20.0, passes=2):
    """Replay ``trace`` against a live gateway ``passes`` times and
    discard the results: compiles the (lane-bucket, length-bucket)
    prefill and (horizon, nb) decode programs the measured replay will
    exercise.  Without this, multi-second jit compiles land inside the
    first requests' TTFT and poison the sim-vs-live calibration.  Two
    passes by default — routing is affinity-stable so the second pass
    mops up the lane-bucket combinations the first pass's co-batch
    timing happened to miss.  Clears the engines' flight recorders
    afterwards so the measured replay's record matching starts from a
    clean pool."""
    from .loadgen import replay

    for _ in range(int(passes)):
        replay(trace, gw, speed=speed)
    for w in gw.workers:
        rec = getattr(getattr(w, "engine", None), "recorder", None)
        if rec is not None:
            rec.clear()


def fleet_report(shapes=("chat", "mixed"), replica_counts=(1, 2, 4),
                 n_requests=48, seed=0, live=False, speed=4.0,
                 slo=None, tolerance=0.15, model=None, num_slots=4,
                 live_replica_counts=(1, 2), warmup=True,
                 live_shape="calib"):
    """The CLI ``fleet`` mode's engine: attainment-vs-replica-count
    curves for each named workload shape (``loadgen.SHAPES``) from one
    invocation, optionally closed against a LIVE CPU-proxy fleet.

    Sim-only (default): the service model comes from ``model``, else
    from the ProgramCard registry, else defaults.  With ``live=True``,
    the ``live_shape`` trace (default the no-abort/no-deadline
    ``calib`` probe, so the gate is not flaky near wall-clock races)
    is replayed against real gateways at ``live_replica_counts``, the
    service model is calibrated from the largest live fleet's replay,
    and a :func:`calibration_report` (ordering exact + attainment
    within ``tolerance``) is attached — the row FLEET_BENCH.json
    commits and check-bench gates."""
    from .loadgen import replay

    slo = slo or SLOSpec()
    shapes = list(shapes)
    replica_counts = [int(n) for n in replica_counts]
    traces = {}
    for name in shapes:
        if name not in SHAPES:
            raise ValueError(f"unknown workload shape {name!r} "
                             f"(known: {sorted(SHAPES)})")
        traces[name] = generate(SHAPES[name](seed=seed,
                                             n_requests=n_requests))

    calibration = None
    live_summaries = {}
    if live:
        live_reports = {}
        probe = live_shape if live_shape in SHAPES else shapes[0]
        live_trace = traces.get(probe)
        if live_trace is None:
            live_trace = generate(SHAPES[probe](seed=seed,
                                                n_requests=n_requests))
        for n in live_replica_counts:
            gw = build_cpu_proxy_gateway(n, seed=seed,
                                         num_slots=num_slots)
            try:
                if warmup:
                    warm_gateway(gw, live_trace, speed=speed)
                live_reports[int(n)] = replay(live_trace, gw,
                                              speed=speed, slo=slo)
            finally:
                gw.shutdown()
        if model is None:
            model = ServiceModel.from_replay(
                live_reports[max(live_reports)])
        calibration = calibration_report(
            live_trace, live_reports, model, speed=speed,
            tolerance=tolerance, num_slots=num_slots)
        calibration["shape"] = probe
        calibration["trace_digest"] = live_trace.digest()
        live_summaries = {
            str(n): {k: v for k, v in rep.items() if k != "records"}
            for n, rep in live_reports.items()}
    if model is None:
        model = ServiceModel.from_program_cards()

    out_shapes = {}
    for name in shapes:
        out_shapes[name] = {
            "spec": dataclasses.asdict(traces[name].spec),
            "trace_digest": traces[name].digest(),
            "curve": attainment_curve(traces[name], replica_counts,
                                      model, speed=speed, slo=slo,
                                      num_slots=num_slots),
        }
    return {
        "shapes": out_shapes,
        "replica_counts": replica_counts,
        "speed": float(speed),
        "slo": {"ttft_s": slo.ttft_s, "tpot_s": slo.tpot_s},
        "service_model": model.to_json(),
        "live": {"enabled": bool(live), "reports": live_summaries},
        "calibration": calibration,
        "ok": calibration is None or calibration["ok"],
    }
