"""Typed process-wide metrics registry (the unified replacement for the
ad-hoc counter dicts PR 2 grew in ``paddle_tpu.profiler``).

Three primitives, all label-aware and thread-safe:

* ``Counter`` — monotonically increasing (compile counts, tokens
  generated, cache hits);
* ``Gauge`` — set-to-current-value (queue depth, active slots);
* ``Histogram`` — fixed cumulative buckets for the prometheus exposition
  PLUS a bounded reservoir of raw samples for exact p50/p95/p99
  (compile seconds, step time, TTFT).

Two exports:

* ``snapshot()`` — one nested JSON-able dict of every metric (and every
  legacy provider), the programmatic surface tests/dashboards poll;
* ``render_prometheus()`` — text exposition (``# HELP``/``# TYPE`` +
  sample lines) for scrape-style collection.

The PR 2 ``profiler.counters()`` provider registry (zero-arg callables
returning ``{counter: value}`` per subsystem) lives HERE now;
``paddle_tpu.profiler`` keeps its ``register_counter_provider`` /
``counters`` names as a back-compat facade over this module.
"""

from __future__ import annotations

import collections
import math
import re
import threading

import numpy as np

#: default histogram bucket upper bounds (seconds-flavored: spans from
#: 100 µs dispatches to multi-minute compiles all land in a real bucket)
DEFAULT_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

#: raw samples kept per (histogram, label set) for exact percentiles
DEFAULT_RESERVOIR = 2048


def _label_key(labels):
    """Canonical hashable key for a label set: sorted (k, v-as-str)."""
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(key):
    return ",".join(f"{k}={v}" for k, v in key)


def _label_prom(key):
    if not key:
        return ""
    quoted = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + quoted + "}"


def _escape(v):
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace(
        "\n", r"\n")


def _escape_help(v):
    """HELP text escapes only backslash and newline (quotes stay raw),
    per the exposition format spec."""
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_value(v):
    """Render a sample value in canonical exposition form: whole
    numbers as ints, non-finite floats as ``NaN``/``+Inf``/``-Inf``
    (Python's ``nan``/``inf`` spellings are not in the grammar)."""
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    i = int(f)
    return str(i) if i == f else repr(f)


def _prom_name(name):
    """Prometheus metric names allow [a-zA-Z0-9_:]; dots become
    underscores (``jit.compile_count`` -> ``jit_compile_count``)."""
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


class Metric:
    """Base: a named family holding one value per label set."""

    kind = "untyped"

    def __init__(self, name, help="", registry=None):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values = {}
        reg = _default_registry if registry is None else registry
        if reg is not None:
            reg._register(self)

    def _slot(self, labels):
        """Get-or-create the value slot for a label set (under lock)."""
        key = _label_key(labels)
        slot = self._values.get(key)
        if slot is None:
            with self._lock:
                slot = self._values.setdefault(key, self._new_slot())
        return slot

    def _new_slot(self):
        raise NotImplementedError

    def label_sets(self):
        return list(self._values.keys())

    def clear(self):
        with self._lock:
            self._values.clear()


class Counter(Metric):
    kind = "counter"

    def _new_slot(self):
        return [0.0]

    def inc(self, amount=1, **labels):
        if amount < 0:
            raise ValueError("Counter can only increase")
        slot = self._slot(labels)
        with self._lock:
            slot[0] += amount

    def value(self, **labels):
        slot = self._values.get(_label_key(labels))
        return 0 if slot is None else _as_scalar(slot[0])

    def snapshot_values(self):
        return {_label_str(k): _as_scalar(v[0])
                for k, v in sorted(self._values.items())}


class Gauge(Metric):
    kind = "gauge"

    def _new_slot(self):
        return [0.0]

    def set(self, value, **labels):
        slot = self._slot(labels)
        with self._lock:
            slot[0] = float(value)

    def inc(self, amount=1, **labels):
        slot = self._slot(labels)
        with self._lock:
            slot[0] += amount

    def dec(self, amount=1, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        slot = self._values.get(_label_key(labels))
        return 0 if slot is None else _as_scalar(slot[0])

    def snapshot_values(self):
        return {_label_str(k): _as_scalar(v[0])
                for k, v in sorted(self._values.items())}


class _HistSlot:
    __slots__ = ("counts", "sum", "count", "samples")

    def __init__(self, n_buckets, reservoir):
        self.counts = [0] * (n_buckets + 1)   # +inf tail bucket
        self.sum = 0.0
        self.count = 0
        self.samples = collections.deque(maxlen=reservoir)


class Histogram(Metric):
    """Fixed-bucket histogram + bounded raw-sample reservoir.

    Buckets are cumulative-le in the prometheus exposition; percentiles
    come from the raw reservoir (exact vs ``np.percentile`` while fewer
    than ``reservoir`` observations have been made, sliding-window
    thereafter)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS,
                 reservoir=DEFAULT_RESERVOIR, registry=None):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.reservoir = int(reservoir)
        super().__init__(name, help=help, registry=registry)

    def _new_slot(self):
        return _HistSlot(len(self.buckets), self.reservoir)

    def observe(self, value, **labels):
        value = float(value)
        slot = self._slot(labels)
        with self._lock:
            i = np.searchsorted(self.buckets, value, side="left")
            slot.counts[i] += 1
            slot.sum += value
            slot.count += 1
            slot.samples.append(value)

    def percentile(self, q, **labels):
        slot = self._values.get(_label_key(labels))
        if slot is None or not slot.samples:
            return None
        return float(np.percentile(np.asarray(slot.samples), q))

    def stats(self, **labels):
        slot = self._values.get(_label_key(labels))
        if slot is None:
            return None
        return self._slot_stats(slot)

    def _slot_stats(self, slot):
        out = {"count": slot.count, "sum": slot.sum}
        if slot.samples:
            arr = np.asarray(slot.samples)
            out["mean"] = float(arr.mean())
            out["p50"], out["p95"], out["p99"] = (
                float(v) for v in np.percentile(arr, (50, 95, 99)))
        cum = 0
        buckets = {}
        for le, c in zip(self.buckets, slot.counts):
            cum += c
            buckets[repr(le)] = cum
        buckets["+Inf"] = cum + slot.counts[-1]
        out["buckets"] = buckets
        return out

    def snapshot_values(self):
        return {_label_str(k): self._slot_stats(v)
                for k, v in sorted(self._values.items())}


class Registry:
    """A named collection of metrics plus the legacy provider registry.

    One process-wide default instance backs the module-level helpers;
    tests can build private registries."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._providers = {}

    # ------------------------------------------------------------ metrics
    def _register(self, metric):
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and type(existing) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{existing.kind}")
            self._metrics[metric.name] = metric

    def _get_or_create(self, cls, name, help, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m
        return cls(name, help=help, registry=self, **kwargs)

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS,
                  reservoir=DEFAULT_RESERVOIR):
        return self._get_or_create(Histogram, name, help,
                                   buckets=buckets, reservoir=reservoir)

    def get(self, name):
        return self._metrics.get(name)

    def metrics(self):
        return dict(self._metrics)

    def value(self, name, /, **labels):
        """Convenience for tests/assertions: the scalar value (Counter/
        Gauge) or stats dict (Histogram) for one (metric, label set).
        ``name`` is positional-only so a label may itself be called
        ``name`` (the span histogram's label scheme)."""
        m = self._metrics.get(name)
        if m is None:
            return None
        if isinstance(m, Histogram):
            return m.stats(**labels)
        return m.value(**labels)

    def reset(self):
        """Drop every recorded value (metric FAMILIES stay registered —
        instrumented modules hold references to them)."""
        with self._lock:
            for m in self._metrics.values():
                m.clear()

    # ------------------------------------------------------- providers
    def register_provider(self, name, provider):
        """Back-compat with PR 2's profiler registry: a zero-arg callable
        returning a flat {counter: value} mapping for one subsystem
        (later registrations replace earlier ones)."""
        if not callable(provider):
            raise TypeError("provider must be callable")
        with self._lock:
            self._providers[name] = provider

    def unregister_provider(self, name):
        with self._lock:
            self._providers.pop(name, None)

    def provider_counters(self):
        """Snapshot every provider: {name: {counter: value}}; a provider
        that raises reports an error string instead of poisoning the
        snapshot."""
        with self._lock:
            items = list(self._providers.items())
        out = {}
        for name, provider in items:
            try:
                out[name] = dict(provider())
            except Exception as e:  # pragma: no cover - defensive
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # --------------------------------------------------------- exports
    def snapshot(self):
        """Nested JSON-able view of everything this registry knows."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        out = {"metrics": {}, "providers": self.provider_counters()}
        for name, m in metrics:
            out["metrics"][name] = {
                "type": m.kind,
                "help": m.help,
                "values": m.snapshot_values(),
            }
        return out

    def render_prometheus(self):
        """Text exposition format; providers render as untyped gauges
        under their subsystem name."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for name, m in metrics:
            pname = _prom_name(name)
            if m.help:
                lines.append(f"# HELP {pname} {_escape_help(m.help)}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if isinstance(m, Histogram):
                for key in sorted(m._values):
                    slot = m._values[key]
                    cum = 0
                    for le, c in zip(m.buckets, slot.counts):
                        cum += c
                        lines.append(
                            f"{pname}_bucket"
                            f"{_label_prom(key + (('le', repr(le)),))} "
                            f"{cum}")
                    lines.append(
                        f"{pname}_bucket"
                        f"{_label_prom(key + (('le', '+Inf'),))} "
                        f"{slot.count}")
                    lines.append(
                        f"{pname}_sum{_label_prom(key)} "
                        f"{_fmt_value(slot.sum)}")
                    lines.append(
                        f"{pname}_count{_label_prom(key)} {slot.count}")
            else:
                for key in sorted(m._values):
                    lines.append(
                        f"{pname}{_label_prom(key)} "
                        f"{_fmt_value(m._values[key][0])}")
        for sub, counters in sorted(self.provider_counters().items()):
            base = _prom_name(sub)
            lines.append(f"# TYPE {base} gauge")
            for cname, v in sorted(counters.items()):
                if isinstance(v, (int, float)):
                    lines.append(
                        f"{base}{{counter=\"{_escape(cname)}\"}} "
                        f"{_fmt_value(v)}")
        return "\n".join(lines) + "\n"


def _as_scalar(v):
    """Counters/gauges hold floats internally; render whole numbers as
    ints so snapshots compare cleanly against expected counts.  NaN and
    infinities (gauges for unavailable analyses) pass through as-is —
    json.dumps spells them NaN/Infinity, like the text exposition."""
    f = float(v)
    if math.isnan(f) or math.isinf(f):
        return f
    i = int(f)
    return i if i == f else f


# ----------------------------------------------------- exposition checker
_EXPO_NAME = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_EXPO_LABEL_NAME = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")
_EXPO_VALUE = re.compile(
    r"(?:[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?|NaN|[+-]?Inf)\Z")
_EXPO_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _parse_labels(raw, errors, lineno):
    """Parse the inside of ``{...}``; returns {name: value} or None on
    error.  Hand-rolled scanner because label VALUES may contain
    escaped quotes/commas a regex split would mangle."""
    labels = {}
    i, n = 0, len(raw)
    while i < n:
        eq = raw.find("=", i)
        if eq < 0:
            errors.append(f"line {lineno}: label without '=': {raw[i:]!r}")
            return None
        lname = raw[i:eq]
        if not _EXPO_LABEL_NAME.match(lname):
            errors.append(f"line {lineno}: bad label name {lname!r}")
            return None
        if eq + 1 >= n or raw[eq + 1] != '"':
            errors.append(f"line {lineno}: label value not quoted")
            return None
        j = eq + 2
        val = []
        while j < n:
            c = raw[j]
            if c == "\\":
                if j + 1 >= n or raw[j + 1] not in ('\\', '"', 'n'):
                    errors.append(
                        f"line {lineno}: bad escape in label value")
                    return None
                val.append({"\\": "\\", '"': '"', "n": "\n"}[raw[j + 1]])
                j += 2
            elif c == '"':
                break
            elif c == "\n":
                errors.append(
                    f"line {lineno}: raw newline in label value")
                return None
            else:
                val.append(c)
                j += 1
        else:
            errors.append(f"line {lineno}: unterminated label value")
            return None
        if lname in labels:
            errors.append(f"line {lineno}: duplicate label {lname!r}")
            return None
        labels[lname] = "".join(val)
        i = j + 1
        if i < n:
            if raw[i] != ",":
                errors.append(
                    f"line {lineno}: expected ',' between labels")
                return None
            i += 1
    return labels


def validate_exposition(text):
    """Parse-check a Prometheus text-exposition document against the
    0.0.4 grammar: comment/HELP/TYPE lines, sample-line shape, metric
    and label name charsets, label-value escaping, value syntax, TYPE
    declared at most once and before its samples, histogram structure
    (``le`` on ``_bucket`` lines), and (family, labels) uniqueness.

    Returns the number of sample lines on success; raises
    ``ValueError`` listing every violation otherwise."""
    errors = []
    types = {}          # family -> declared type
    seen_samples = set()  # (name, sorted label items)
    families_emitted = set()
    n_samples = 0
    for lineno, line in enumerate(text.split("\n"), start=1):
        if not line:
            continue
        if line != line.strip():
            errors.append(f"line {lineno}: leading/trailing whitespace")
            line = line.strip()
            if not line:
                continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3 or not _EXPO_NAME.match(parts[2]):
                    errors.append(f"line {lineno}: bad {parts[1]} line")
                    continue
                if parts[1] == "TYPE":
                    mtype = parts[3].strip() if len(parts) > 3 else ""
                    if mtype not in _EXPO_TYPES:
                        errors.append(
                            f"line {lineno}: unknown type {mtype!r}")
                    if parts[2] in types:
                        errors.append(
                            f"line {lineno}: duplicate TYPE for "
                            f"{parts[2]!r}")
                    if parts[2] in families_emitted:
                        errors.append(
                            f"line {lineno}: TYPE for {parts[2]!r} "
                            "after its samples")
                    types[parts[2]] = mtype
            continue  # other comments are free-form
        # ---- sample line: name[{labels}] value [timestamp]
        rest = line
        brace = rest.find("{")
        if brace >= 0:
            name = rest[:brace]
            close = rest.rfind("}")
            if close < brace:
                errors.append(f"line {lineno}: unbalanced braces")
                continue
            labels = _parse_labels(rest[brace + 1:close], errors, lineno)
            if labels is None:
                continue
            tail = rest[close + 1:].split()
        else:
            fields = rest.split()
            name, labels, tail = fields[0], {}, fields[1:]
        if not _EXPO_NAME.match(name):
            errors.append(f"line {lineno}: bad metric name {name!r}")
            continue
        if not tail or len(tail) > 2:
            errors.append(f"line {lineno}: expected 'value [timestamp]'")
            continue
        if not _EXPO_VALUE.match(tail[0]):
            errors.append(f"line {lineno}: bad value {tail[0]!r}")
        if len(tail) == 2 and not re.match(r"-?\d+\Z", tail[1]):
            errors.append(f"line {lineno}: bad timestamp {tail[1]!r}")
        # family resolution: histogram samples append _bucket/_sum/_count
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) in ("histogram", "summary"):
                family = base
                if (suffix == "_bucket"
                        and types.get(base) == "histogram"
                        and "le" not in labels):
                    errors.append(
                        f"line {lineno}: histogram _bucket without "
                        "'le' label")
                break
        families_emitted.add(family)
        key = (name, tuple(sorted(labels.items())))
        if key in seen_samples:
            errors.append(
                f"line {lineno}: duplicate sample {name}{labels}")
        seen_samples.add(key)
        n_samples += 1
    if errors:
        raise ValueError(
            "invalid exposition:\n  " + "\n  ".join(errors))
    return n_samples


# ---------------------------------------------------------------- default
_default_registry = None          # so Metric.__init__ sees a name
_default_registry = Registry()


def default_registry():
    return _default_registry


def counter(name, help=""):
    return _default_registry.counter(name, help)


def gauge(name, help=""):
    return _default_registry.gauge(name, help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS,
              reservoir=DEFAULT_RESERVOIR):
    return _default_registry.histogram(name, help, buckets=buckets,
                                       reservoir=reservoir)


def value(name, /, **labels):
    return _default_registry.value(name, **labels)


def snapshot():
    return _default_registry.snapshot()


def render_prometheus():
    return _default_registry.render_prometheus()


def reset():
    _default_registry.reset()


def register_provider(name, provider):
    _default_registry.register_provider(name, provider)


def unregister_provider(name):
    _default_registry.unregister_provider(name)


def provider_counters():
    return _default_registry.provider_counters()
