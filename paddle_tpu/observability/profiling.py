"""Program cards: per-compiled-program cost dossiers for the serving
engine (observability phase 3).

Every compiled serving program — each prefill ``(lanes, bucket)`` pair,
each decode ``(horizon, nb, K)`` triple — gets ONE card at its first
compile, capturing what the compiler itself knows about the program:

* XLA ``cost_analysis()`` — FLOPs and bytes accessed per dispatch
  (the probe pattern jit/train_step.py established: prefer the
  compiled executable's analysis, fall back to the HLO-level one, and
  record honest ``None`` when a backend offers neither);
* ``memory_analysis()`` — argument/output/temp/code bytes of the
  executable (``CompiledMemoryStats``), i.e. the program's static
  device-memory footprint;
* wall-clock compile seconds and static metadata the caller supplies
  (bucket key, donated bytes, lane count, ...).

Cards live in a process-wide :class:`ProgramCardRegistry` keyed by
``(fn, signature-hash)`` so repeated engine construction with the same
shapes never re-probes (the probe costs one extra XLA compile — see
``capture()``).  The registry publishes ``compile.*`` gauges per card
(``NaN`` where an analysis is unavailable on the backend — the
exposition format has a spelling for that, and dashboards should see
"unknown", not 0), feeds the ``/debug/programs`` telemetry endpoint,
and renders as ``python -m paddle_tpu.observability programs``.

The cards are also the engine's cost model: per-dispatch FLOP/byte
totals divided over the lanes that rode the dispatch become the
per-request cost attribution in ``RequestTrace`` (engine.py), and
bytes-accessed over dispatch wall time becomes the live
achieved-vs-roofline gauge (memory.py supplies the bandwidth).
"""

from __future__ import annotations

import threading
import time

from . import events as _events
from . import metrics as _metrics

#: per-program gauges, labeled (fn, key); value NaN = analysis
#: unavailable on this backend
_CARD_FLOPS = _metrics.gauge(
    "compile.program_flops",
    "XLA cost-analysis FLOPs per dispatch of a compiled program")
_CARD_BYTES = _metrics.gauge(
    "compile.program_bytes_accessed",
    "XLA cost-analysis bytes accessed per dispatch of a compiled program")
_CARD_SECONDS = _metrics.gauge(
    "compile.program_compile_seconds",
    "wall seconds the first compile of this program took")
_CARD_ARG_BYTES = _metrics.gauge(
    "compile.program_argument_bytes",
    "executable argument bytes (memory_analysis)")
_CARD_TEMP_BYTES = _metrics.gauge(
    "compile.program_temp_bytes",
    "executable scratch/temp bytes (memory_analysis)")
_CARD_COUNT = _metrics.gauge(
    "compile.programs", "program cards captured, by function")


def _nan_if_none(v):
    return float("nan") if v is None else float(v)


class ProgramCard:
    """The cost dossier of ONE compiled program."""

    __slots__ = ("fn", "key", "backend", "flops", "bytes_accessed",
                 "compile_seconds", "donated_bytes", "argument_bytes",
                 "output_bytes", "temp_bytes", "generated_code_bytes",
                 "meta", "created_wall", "dispatches", "analysis_source",
                 "comms")

    def __init__(self, fn, key, backend="", flops=None,
                 bytes_accessed=None, compile_seconds=0.0,
                 donated_bytes=0, argument_bytes=None, output_bytes=None,
                 temp_bytes=None, generated_code_bytes=None, meta=None,
                 analysis_source=None, comms=None):
        self.fn = fn
        self.key = key
        self.backend = backend
        self.flops = None if flops is None else float(flops)
        self.bytes_accessed = (None if bytes_accessed is None
                               else float(bytes_accessed))
        self.compile_seconds = float(compile_seconds)
        self.donated_bytes = int(donated_bytes)
        self.argument_bytes = argument_bytes
        self.output_bytes = output_bytes
        self.temp_bytes = temp_bytes
        self.generated_code_bytes = generated_code_bytes
        self.meta = dict(meta or {})
        self.created_wall = time.time()
        self.dispatches = 0          # bumped by the owner per call
        self.analysis_source = analysis_source
        # phase 4: comms.analyze_jaxpr(...).to_json() of the traced
        # program, when the caller ran the walker; None = not analyzed
        self.comms = comms

    def to_json(self):
        return {
            "fn": self.fn,
            "key": self.key,
            "backend": self.backend,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "compile_seconds": round(self.compile_seconds, 6),
            "donated_bytes": self.donated_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "analysis_source": self.analysis_source,
            "comms": self.comms,
            "dispatches": self.dispatches,
            "created_wall": self.created_wall,
            "meta": dict(self.meta),
        }


class ProgramCardRegistry:
    """Process-wide card store keyed by ``(fn, key)``.

    ``record()`` publishes the card's ``compile.*`` gauges; ``get()``
    lets a CompiledFn skip the probe when an identical program (same
    function, same signature) was already carded by an earlier engine
    in this process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cards = {}             # (fn, key) -> ProgramCard

    def record(self, card):
        with self._lock:
            self._cards[(card.fn, card.key)] = card
        labels = dict(fn=card.fn, key=card.key)
        _CARD_FLOPS.set(_nan_if_none(card.flops), **labels)
        _CARD_BYTES.set(_nan_if_none(card.bytes_accessed), **labels)
        _CARD_SECONDS.set(card.compile_seconds, **labels)
        _CARD_ARG_BYTES.set(_nan_if_none(card.argument_bytes), **labels)
        _CARD_TEMP_BYTES.set(_nan_if_none(card.temp_bytes), **labels)
        with self._lock:
            per_fn = sum(1 for f, _ in self._cards if f == card.fn)
        _CARD_COUNT.set(per_fn, fn=card.fn)
        return card

    def get(self, fn, key):
        with self._lock:
            return self._cards.get((fn, key))

    def cards(self, fn=None):
        with self._lock:
            out = list(self._cards.values())
        if fn is not None:
            out = [c for c in out if c.fn == fn]
        return sorted(out, key=lambda c: (c.fn, c.key))

    def __len__(self):
        with self._lock:
            return len(self._cards)

    def clear(self):
        with self._lock:
            self._cards.clear()

    def to_json(self):
        cards = self.cards()
        return {
            "count": len(cards),
            "total_flops_dispatched": sum(
                c.flops * c.dispatches for c in cards
                if c.flops is not None),
            "total_bytes_dispatched": sum(
                c.bytes_accessed * c.dispatches for c in cards
                if c.bytes_accessed is not None),
            "cards": [c.to_json() for c in cards],
        }

    def render_text(self):
        """Human-readable table for the CLI."""
        cards = self.cards()
        if not cards:
            return "no program cards captured\n"
        rows = [("fn", "key", "flops", "bytes", "compile_s",
                 "dispatches", "meta")]
        for c in cards:
            rows.append((
                c.fn, c.key,
                _fmt_quantity(c.flops), _fmt_quantity(c.bytes_accessed),
                f"{c.compile_seconds:.3f}", str(c.dispatches),
                ",".join(f"{k}={v}" for k, v in sorted(c.meta.items()))))
        widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
        lines = ["  ".join(cell.ljust(w) for cell, w in zip(r, widths))
                 for r in rows]
        return "\n".join(lines) + "\n"


def _fmt_quantity(v):
    if v is None:
        return "n/a"
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if v >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def _scalar_analysis(analysis):
    """Normalize jax's cost_analysis return shape: a dict, or a
    per-device list of dicts (take device 0), or None."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    return analysis if isinstance(analysis, dict) else None


def analyze_lowered(lowered, deep=False):
    """Extract (flops, bytes_accessed, memory-stats dict, source) from a
    ``jax.stages.Lowered``.

    ``deep=True`` compiles the program and reads the executable's
    analyses (optimized HLO plus ``memory_analysis`` — the
    train_step.cost_analysis probe pattern; ``lowered.compile()`` may
    re-run XLA, which is why callers memoize cards process-wide and
    only go deep on accelerator backends).  ``deep=False`` stays on the
    HLO-level ``lowered.cost_analysis()`` — no extra compile, same
    flops/bytes-accessed numbers on CPU, but no memory stats.  Returns
    all-None when the backend offers neither."""
    cost = mem = None
    source = None
    if deep:
        try:
            compiled = lowered.compile()
        except Exception:
            compiled = None
        if compiled is not None:
            try:
                cost = _scalar_analysis(compiled.cost_analysis())
                source = "compiled"
            except Exception:
                cost = None
            try:
                mem = compiled.memory_analysis()
            except Exception:
                mem = None
    if cost is None:
        try:
            cost = _scalar_analysis(lowered.cost_analysis())
            source = "lowered" if cost is not None else None
        except Exception:
            cost = None
    flops = bytes_accessed = None
    if cost:
        flops = cost.get("flops")
        bytes_accessed = cost.get("bytes accessed",
                                  cost.get("bytes_accessed"))
    stats = {}
    if mem is not None:
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes",
                      "generated_code_size_in_bytes"):
            stats[field] = getattr(mem, field, None)
    return flops, bytes_accessed, stats, source


def capture(fn_name, key, lowered, compile_seconds=0.0, donated_bytes=0,
            meta=None, backend="", registry=None, deep=None, comms=None):
    """Build + record one ProgramCard from a ``Lowered``; never raises
    (a backend without analyses still yields a card with Nones, and any
    probe failure degrades the same way).  ``deep=None`` auto-selects:
    the compile-probe (memory stats, optimized-HLO cost) on accelerator
    backends, the free HLO-level estimate on cpu — so test suites never
    pay a second XLA compile per program.

    ``comms`` (phase 4) attaches a collective census to the card: pass
    the ``comms.CommsReport`` of the traced program (its ``comms.*``
    counters are published once, here) or an already-rendered dict."""
    reg = registry if registry is not None else _default_registry
    if deep is None:
        deep = backend not in ("", "cpu")
    try:
        flops, bytes_accessed, stats, source = analyze_lowered(
            lowered, deep=deep)
    except Exception:                # pragma: no cover - defensive
        flops = bytes_accessed = source = None
        stats = {}
    if comms is not None and hasattr(comms, "to_json"):
        try:
            comms = comms.publish().to_json()
        except Exception:            # pragma: no cover - defensive
            comms = None
    card = ProgramCard(
        fn_name, key, backend=backend, flops=flops,
        bytes_accessed=bytes_accessed, compile_seconds=compile_seconds,
        donated_bytes=donated_bytes,
        argument_bytes=stats.get("argument_size_in_bytes"),
        output_bytes=stats.get("output_size_in_bytes"),
        temp_bytes=stats.get("temp_size_in_bytes"),
        generated_code_bytes=stats.get("generated_code_size_in_bytes"),
        meta=meta, analysis_source=source, comms=comms)
    reg.record(card)
    _events.instant("compile.program_card", cat="observability",
                    fn=fn_name, key=key,
                    flops=flops, bytes_accessed=bytes_accessed,
                    seconds=round(float(compile_seconds), 6))
    return card


_default_registry = ProgramCardRegistry()


def default_registry():
    return _default_registry


def cards(fn=None):
    return _default_registry.cards(fn)


def to_json():
    return _default_registry.to_json()


def render_text():
    return _default_registry.render_text()


def clear():
    _default_registry.clear()
