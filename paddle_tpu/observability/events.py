"""Bounded structured event timeline + chrome-trace export.

Every subsystem appends typed host events here — compile begin/end with
the aval signature and wall seconds, retrace causes, dataloader stalls,
serving slot alloc/retire/EOS, checkpoint saves — into ONE process-wide
ring buffer (old events fall off; recording never blocks or grows
unboundedly).

``export_chrome_trace()`` emits the Chrome Trace Event JSON format
(``{"traceEvents": [...]}``, ts in microseconds, ``B``/``E``/``i``
phases), loadable in ``chrome://tracing`` / Perfetto — drop it next to a
``jax.profiler`` device trace and the host timeline interleaves with the
XLA one.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

DEFAULT_CAPACITY = 4096

#: phases (chrome trace event ``ph`` values)
BEGIN = "B"
END = "E"
INSTANT = "i"
COMPLETE = "X"
#: async phases — for spans that overlap rather than nest on one thread
#: (e.g. serving requests living across many engine steps); require an
#: ``id`` correlating the pair
ASYNC_BEGIN = "b"
ASYNC_END = "e"


class Event:
    """One timeline entry. ``ts`` is ``time.time()`` seconds (wall clock,
    so host events line up with device-trace timestamps); ``dur`` is
    seconds for COMPLETE events, None otherwise."""

    __slots__ = ("name", "phase", "ts", "dur", "cat", "tid", "args", "id")

    def __init__(self, name, phase=INSTANT, ts=None, dur=None, cat="host",
                 tid=None, args=None, id=None):
        self.name = name
        self.phase = phase
        self.ts = time.time() if ts is None else ts
        self.dur = dur
        self.cat = cat
        self.tid = threading.get_ident() if tid is None else tid
        self.args = dict(args) if args else {}
        self.id = id

    def to_chrome(self):
        ev = {
            "name": self.name,
            "ph": self.phase,
            "ts": self.ts * 1e6,          # chrome trace wants microseconds
            "pid": os.getpid(),
            "tid": self.tid,
            "cat": self.cat,
        }
        if self.phase == COMPLETE:
            ev["dur"] = (self.dur or 0.0) * 1e6
        if self.phase == INSTANT:
            ev["s"] = "t"                  # thread-scoped instant
        if self.id is not None:
            ev["id"] = str(self.id)
        if self.args:
            ev["args"] = {k: _jsonable(v) for k, v in self.args.items()}
        return ev

    def __repr__(self):
        return (f"Event({self.name!r}, ph={self.phase}, ts={self.ts:.6f}, "
                f"args={self.args})")


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return repr(v)


class EventLog:
    """Thread-safe bounded ring buffer of Events."""

    def __init__(self, capacity=DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=capacity)
        self._dropped = 0

    @property
    def capacity(self):
        return self._ring.maxlen

    def set_capacity(self, capacity):
        with self._lock:
            old = list(self._ring)
            self._ring = collections.deque(old[-capacity:],
                                           maxlen=int(capacity))

    def record(self, name, phase=INSTANT, cat="host", dur=None, args=None,
               ts=None, id=None):
        ev = Event(name, phase=phase, ts=ts, dur=dur, cat=cat, args=args,
                   id=id)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(ev)
        return ev

    def begin(self, name, cat="host", **args):
        return self.record(name, phase=BEGIN, cat=cat, args=args)

    def end(self, name, cat="host", **args):
        return self.record(name, phase=END, cat=cat, args=args)

    def instant(self, name, cat="host", **args):
        return self.record(name, phase=INSTANT, cat=cat, args=args)

    def events(self, name=None, cat=None):
        with self._lock:
            evs = list(self._ring)
        if name is not None:
            evs = [e for e in evs if e.name == name]
        if cat is not None:
            evs = [e for e in evs if e.cat == cat]
        return evs

    @property
    def dropped(self):
        return self._dropped

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    def export_chrome_trace(self, file=None, extra=None):
        """Chrome Trace Event JSON for the current ring contents, sorted
        by timestamp (chrome requires monotonically non-decreasing ts
        within a (pid, tid); sorting globally satisfies the stricter
        whole-file ordering our tests assert). ``file`` may be a path or
        a writable file object; returns the JSON string either way.

        ``extra`` merges pre-rendered chrome events (dicts with a
        ``ts`` in µs — e.g. the flight recorder's per-request async
        spans) into the same timeline.  The metadata header carries
        ``dropped_events`` plus process identity (``process_name``,
        ``git_sha``) so a truncated ring or a stale build is visible
        right in Perfetto."""
        chrome = [e.to_chrome() for e in self.events()]
        if extra:
            chrome.extend(extra)
        chrome.sort(key=lambda e: e["ts"])
        doc = {
            "traceEvents": chrome,
            "displayTimeUnit": "ms",
            "metadata": {"producer": "paddle_tpu.observability",
                         "dropped_events": self._dropped,
                         "process_name": _process_name(),
                         "git_sha": _git_sha(),
                         "mesh": _mesh_meta()},
        }
        text = json.dumps(doc)
        if file is not None:
            if hasattr(file, "write"):
                file.write(text)
            else:
                with open(file, "w") as f:
                    f.write(text)
        return text


def _process_name():
    import sys

    return f"python:{os.path.basename(sys.argv[0] or 'interactive')}"


def _mesh_meta():
    """Mesh summary for the trace header (world size, mesh shape,
    parallel mode) when a HybridCommunicateGroup is live; None
    otherwise.  Lazy + guarded: trace export must never fail because
    the distributed stack is absent or half-initialized."""
    try:
        from . import comms

        return comms.mesh_meta()
    except Exception:                # pragma: no cover - defensive
        return None


_GIT_SHA = None


def _git_sha():
    """Short git SHA of the working tree, best-effort and cached (trace
    export must never fail or block on a missing git)."""
    global _GIT_SHA
    if _GIT_SHA is None:
        try:
            import subprocess

            _GIT_SHA = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            ).stdout.strip() or "unknown"
        except Exception:
            _GIT_SHA = "unknown"
    return _GIT_SHA


# ------------------------------------------------------------- default log
_default_log = EventLog()


def default_log():
    return _default_log


def record(name, phase=INSTANT, cat="host", dur=None, args=None, ts=None,
           id=None):
    return _default_log.record(name, phase=phase, cat=cat, dur=dur,
                               args=args, ts=ts, id=id)


def begin(name, cat="host", **args):
    return _default_log.begin(name, cat=cat, **args)


def end(name, cat="host", **args):
    return _default_log.end(name, cat=cat, **args)


def instant(name, cat="host", **args):
    return _default_log.instant(name, cat=cat, **args)


def events(name=None, cat=None):
    return _default_log.events(name=name, cat=cat)


def clear():
    _default_log.clear()


def set_capacity(capacity):
    _default_log.set_capacity(capacity)


def export_chrome_trace(file=None, extra=None):
    return _default_log.export_chrome_trace(file=file, extra=extra)
