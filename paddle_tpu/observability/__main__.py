"""``python -m paddle_tpu.observability`` — dump the process's live
observability state.

Modes:
  snapshot      nested JSON of every metric + legacy provider (default)
  prometheus    text exposition (# HELP / # TYPE / samples)
  trace         chrome-trace JSON of the event timeline
  programs      program-card registry: per-compiled-program FLOPs,
                bytes-accessed, compile seconds (--json for raw dump)
  mesh          live HybridCommunicateGroup topology (axes, dims, comm
                rank-lists) + the collective-comms ledger, as JSON —
                the CLI twin of the ``/debug/mesh`` endpoint
  check-bench   bench-regression gate: compare a fresh bench document
                (--fresh, from ``bench_decode.py --out`` or
                ``bench_models.py bench_multichip_comms --out``)
                against the committed baseline (--baseline /
                --bench-file, DECODE_BENCH.json, MULTICHIP_BENCH.json
                or FLEET_BENCH.json); exits 1 on an unallowed
                regression
  fleet         fleet observatory: generate seeded workload traces
                (--shapes, e.g. chat,mixed), run the discrete-event
                capacity simulator across --replicas, and print
                SLO-attainment-vs-replica-count curves as JSON; with
                --live, also replay the first shape against real
                CPU-proxy gateways over HTTP/SSE and attach the
                sim-vs-live calibration report (exits 1 when the
                calibration gate fails)
  serve         start the telemetry HTTP endpoint (blocks; --port,
                --duration to exit after N seconds)

``-o FILE`` writes to a file instead of stdout. ``--exec SCRIPT`` runs a
Python file first (in this process), so the dump reflects an actual
workload — the one-process analog of scraping a serving worker. With
``serve``, ``--exec`` runs the script while the endpoint is already up,
so it can be scraped mid-workload.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description="dump paddle_tpu observability state")
    parser.add_argument("mode", nargs="?", default="snapshot",
                        choices=("snapshot", "prometheus", "trace",
                                 "programs", "mesh", "check-bench",
                                 "fleet", "serve"))
    parser.add_argument("-o", "--output", default=None,
                        help="write to FILE instead of stdout")
    parser.add_argument("--exec", dest="script", default=None,
                        help="run a Python script first, then dump")
    parser.add_argument("--json", action="store_true",
                        help="programs mode: raw JSON instead of a table")
    parser.add_argument("--baseline", default="DECODE_BENCH.json",
                        help="check-bench: committed baseline document")
    parser.add_argument("--bench-file", default=None,
                        help="check-bench: gate against this committed "
                        "bench document instead of --baseline (e.g. "
                        "MULTICHIP_BENCH.json)")
    parser.add_argument("--fresh", default=None,
                        help="check-bench: fresh bench document "
                        "(bench_decode.py --out FILE)")
    parser.add_argument("--tolerance", type=float, default=0.25,
                        help="check-bench: relative tolerance on the "
                        "timing-derived primary value (0.25 = 25%%)")
    parser.add_argument("--det-tolerance", type=float, default=0.0,
                        help="check-bench: tolerance on deterministic "
                        "fields (bytes/compile/dispatch counts)")
    parser.add_argument("--allow-regress", action="append", default=[],
                        help="check-bench: substring of metric[::field] "
                        "whose regression is acknowledged (repeatable)")
    parser.add_argument("--port", type=int, default=9400,
                        help="serve mode: port to bind (0 = ephemeral)")
    parser.add_argument("--duration", type=float, default=None,
                        help="serve mode: exit after N seconds "
                        "(default: serve until interrupted)")
    parser.add_argument("--shapes", default="chat,mixed",
                        help="fleet mode: comma-separated workload "
                        "shapes (chat, mixed)")
    parser.add_argument("--replicas", default="1,2,4",
                        help="fleet mode: comma-separated replica "
                        "counts for the attainment curve")
    parser.add_argument("--requests", type=int, default=48,
                        help="fleet mode: requests per workload trace")
    parser.add_argument("--seed", type=int, default=0,
                        help="fleet mode: workload trace seed")
    parser.add_argument("--live", action="store_true",
                        help="fleet mode: also replay against live "
                        "CPU-proxy gateways and attach the sim-vs-live "
                        "calibration report")
    parser.add_argument("--speed", type=float, default=4.0,
                        help="fleet mode: virtual-time compression for "
                        "replay/sim timelines (higher = burstier wall-"
                        "clock load; keep moderate with --live so the "
                        "shared-core CPU proxy stays uncontended)")
    parser.add_argument("--slo-ttft", type=float, default=2.0,
                        help="fleet mode: TTFT attainment threshold "
                        "(wall seconds at replay speed)")
    parser.add_argument("--slo-tpot", type=float, default=0.5,
                        help="fleet mode: per-token attainment "
                        "threshold (wall seconds)")
    parser.add_argument("--fleet-tolerance", type=float, default=0.25,
                        help="fleet mode: sim-vs-live attainment "
                        "tolerance for the calibration gate")
    args = parser.parse_args(argv)

    if args.mode == "serve":
        return _serve(args)
    if args.mode == "check-bench":
        return _check_bench(args)
    if args.mode == "fleet":
        return _fleet(args)

    if args.script:
        with open(args.script) as f:
            code = compile(f.read(), args.script, "exec")
        exec(code, {"__name__": "__main__", "__file__": args.script})

    from . import events, metrics

    if args.mode == "snapshot":
        text = json.dumps(metrics.snapshot(), indent=2, default=repr)
    elif args.mode == "prometheus":
        text = metrics.render_prometheus()
    elif args.mode == "programs":
        from . import profiling

        text = (json.dumps(profiling.to_json(), indent=2, default=repr)
                if args.json else profiling.render_text())
    elif args.mode == "mesh":
        from . import comms

        text = json.dumps(comms.mesh_json(), indent=2, default=repr)
    else:
        text = events.export_chrome_trace()

    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text if text.endswith("\n") else text + "\n")
    return 0


def _check_bench(args):
    from . import regression

    if not args.fresh:
        print("check-bench: --fresh FILE is required "
              "(produce one with benchmarks/bench_decode.py --out)",
              file=sys.stderr)
        return 2
    report = regression.check_bench(
        args.baseline, args.fresh, tolerance=args.tolerance,
        det_tolerance=args.det_tolerance,
        allow_regress=args.allow_regress,
        bench_file=args.bench_file)
    text = regression.render_text(report)
    if args.output:
        with open(args.output, "w") as f:
            f.write(json.dumps(report, indent=2) + "\n")
    sys.stdout.write(text)
    return 0 if report["ok"] else 1


def _fleet(args):
    from . import fleetsim, loadgen

    report = fleetsim.fleet_report(
        shapes=[s.strip() for s in args.shapes.split(",") if s.strip()],
        replica_counts=[int(n) for n in args.replicas.split(",")],
        n_requests=args.requests, seed=args.seed, live=args.live,
        speed=args.speed,
        slo=loadgen.SLOSpec(ttft_s=args.slo_ttft,
                            tpot_s=args.slo_tpot),
        tolerance=args.fleet_tolerance)
    text = json.dumps(report, indent=2, default=repr) + "\n"
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0 if report["ok"] else 1


def _serve(args):
    import time

    from .server import TelemetryServer

    srv = TelemetryServer(port=args.port).start()
    print(f"telemetry listening on {srv.url()} "
          f"(endpoints: /metrics /healthz /readyz /debug/requests "
          f"/debug/slo /debug/programs /trace)", flush=True)
    try:
        if args.script:
            with open(args.script) as f:
                code = compile(f.read(), args.script, "exec")
            exec(code, {"__name__": "__main__", "__file__": args.script})
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
