"""``python -m paddle_tpu.observability`` — dump the process's live
observability state.

Modes:
  snapshot      nested JSON of every metric + legacy provider (default)
  prometheus    text exposition (# HELP / # TYPE / samples)
  trace         chrome-trace JSON of the event timeline
  serve         start the telemetry HTTP endpoint (blocks; --port,
                --duration to exit after N seconds)

``-o FILE`` writes to a file instead of stdout. ``--exec SCRIPT`` runs a
Python file first (in this process), so the dump reflects an actual
workload — the one-process analog of scraping a serving worker. With
``serve``, ``--exec`` runs the script while the endpoint is already up,
so it can be scraped mid-workload.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description="dump paddle_tpu observability state")
    parser.add_argument("mode", nargs="?", default="snapshot",
                        choices=("snapshot", "prometheus", "trace",
                                 "serve"))
    parser.add_argument("-o", "--output", default=None,
                        help="write to FILE instead of stdout")
    parser.add_argument("--exec", dest="script", default=None,
                        help="run a Python script first, then dump")
    parser.add_argument("--port", type=int, default=9400,
                        help="serve mode: port to bind (0 = ephemeral)")
    parser.add_argument("--duration", type=float, default=None,
                        help="serve mode: exit after N seconds "
                        "(default: serve until interrupted)")
    args = parser.parse_args(argv)

    if args.mode == "serve":
        return _serve(args)

    if args.script:
        with open(args.script) as f:
            code = compile(f.read(), args.script, "exec")
        exec(code, {"__name__": "__main__", "__file__": args.script})

    from . import events, metrics

    if args.mode == "snapshot":
        text = json.dumps(metrics.snapshot(), indent=2, default=repr)
    elif args.mode == "prometheus":
        text = metrics.render_prometheus()
    else:
        text = events.export_chrome_trace()

    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text if text.endswith("\n") else text + "\n")
    return 0


def _serve(args):
    import time

    from .server import TelemetryServer

    srv = TelemetryServer(port=args.port).start()
    print(f"telemetry listening on {srv.url()} "
          f"(endpoints: /metrics /healthz /readyz /debug/requests "
          f"/debug/slo /trace)", flush=True)
    try:
        if args.script:
            with open(args.script) as f:
                code = compile(f.read(), args.script, "exec")
            exec(code, {"__name__": "__main__", "__file__": args.script})
        if args.duration is not None:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
