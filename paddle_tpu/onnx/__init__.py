"""paddle.onnx (ref: paddle.onnx.export -> paddle2onnx (U)). The TPU
build's model-interchange format is StableHLO, not ONNX: the `onnx`
package does not exist in this environment and XLA consumes StableHLO
natively, so `export` here produces the SAME portable artifact
`paddle_tpu.jit.save` writes (serialized StableHLO + weights), loadable
by `paddle_tpu.jit.load` and servable by `paddle_tpu.inference`
Predictors. The function works — models exported through this API round
-trip through the inference stack — but the on-disk format is
`<path>.pdmodel` (StableHLO), NOT an `.onnx` protobuf; a consumer that
needs true ONNX must run paddle2onnx against the reference framework.
"""

from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` as a portable serving artifact (StableHLO).

    Signature-compatible with the reference `paddle.onnx.export`:
    `opset_version` and extra configs are accepted and ignored (they
    parameterize the ONNX opset, which does not apply to StableHLO).
    `path` follows the reference convention of a prefix WITHOUT the
    format suffix; the artifact lands at `<path>.pdmodel` +
    `<path>.pdiparams`. Returns the path prefix.
    """
    if input_spec is None:
        raise ValueError(
            "paddle.onnx.export requires input_spec (the reference "
            "requires it for dynamic-graph export too)")
    if path.endswith(".onnx"):
        path = path[: -len(".onnx")]
    warnings.warn(
        "paddle_tpu.onnx.export writes a StableHLO artifact "
        f"('{path}.pdmodel'), not an ONNX protobuf — StableHLO is this "
        "build's interchange format (loadable via jit.load, servable "
        "via paddle_tpu.inference).", stacklevel=2)
    from ..jit.api import save as jit_save

    jit_save(layer, path, input_spec=input_spec)
    return path
