"""paddle.onnx stub: on the TPU build the export interchange format is
StableHLO via paddle_tpu.jit.save (jax.export), not ONNX."""


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is replaced by StableHLO export: use paddle_tpu.jit.save"
    )
