"""Flags registry (ref: PHI_DEFINE_EXPORTED_* gflags + paddle.set_flags,
SURVEY.md §2.1 N21). One typed Python registry with FLAGS_* env ingestion and
XLA_FLAGS passthrough — replaces the reference's three-tier native system.
"""

from __future__ import annotations

import os
from typing import Any, Dict


class _Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name, default, help=""):
        self.name = name
        self.default = default
        self.value = default
        self.type = type(default)
        self.help = help


class FlagRegistry:
    def __init__(self):
        self._flags: Dict[str, _Flag] = {}

    def define(self, name: str, default: Any, help: str = ""):
        name = self._norm(name)
        if name not in self._flags:
            self._flags[name] = _Flag(name, default, help)
            env = os.environ.get(f"FLAGS_{name}")
            if env is not None:
                self._flags[name].value = self._parse(env, default)
        return self._flags[name].value

    @staticmethod
    def _norm(name: str) -> str:
        return name[6:] if name.startswith("FLAGS_") else name

    @staticmethod
    def _parse(text: str, default: Any):
        if isinstance(default, bool):
            return text.lower() in ("1", "true", "yes", "on")
        if isinstance(default, int):
            return int(text)
        if isinstance(default, float):
            return float(text)
        return text

    def set_flags(self, flags: Dict[str, Any]):
        for k, v in flags.items():
            k = self._norm(k)
            if k not in self._flags:
                self._flags[k] = _Flag(k, v)
            else:
                self._flags[k].value = v

    def get_flags(self, names=None):
        if names is None:
            names = list(self._flags)
        if isinstance(names, str):
            names = [names]
        return {f"FLAGS_{self._norm(n)}": self._flags[self._norm(n)].value for n in names if self._norm(n) in self._flags}

    def __getitem__(self, name):
        return self._flags[self._norm(name)].value


GLOBAL_FLAGS = FlagRegistry()

# Core flags (parity with the reference's most-used FLAGS_*)
GLOBAL_FLAGS.define("check_nan_inf", False, "scan op outputs for nan/inf (jax.debug_nans analog)")
GLOBAL_FLAGS.define("allocator_strategy", "xla_bfc", "informational; XLA owns device memory on TPU")
GLOBAL_FLAGS.define("deterministic", True, "TPU/XLA is deterministic by default")
GLOBAL_FLAGS.define("embedding_deterministic", 1, "")
GLOBAL_FLAGS.define("log_level", "INFO", "")


def set_flags(flags):
    GLOBAL_FLAGS.set_flags(flags)
    if GLOBAL_FLAGS["check_nan_inf"]:
        import jax

        jax.config.update("jax_debug_nans", True)


def get_flags(names=None):
    return GLOBAL_FLAGS.get_flags(names)
