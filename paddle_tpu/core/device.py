"""Device management (ref: python/paddle/device/ (U), paddle.set_device).

On TPU there is no CUDAPlace/stream zoo to manage — XLA/PJRT owns placement —
so this is a thin veneer over jax.devices() that preserves the Paddle API.
"""

from __future__ import annotations

import jax


class Place:
    def __init__(self, device):
        self._device = device

    @property
    def platform(self):
        return self._device.platform

    def __repr__(self):
        return f"Place({self._device})"


_CURRENT = [None]


def set_device(device: str):
    """Accepts 'tpu', 'cpu', 'tpu:0' etc. Returns the Place."""
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    if name in ("gpu", "cuda", "xpu"):
        name = _default_platform()  # gracefully map reference device names
    devs = [d for d in jax.devices() if d.platform == name] or jax.devices()
    _CURRENT[0] = Place(devs[min(idx, len(devs) - 1)])
    return _CURRENT[0]


def _default_platform():
    return jax.devices()[0].platform


def get_device() -> str:
    if _CURRENT[0] is None:
        d = jax.devices()[0]
        return f"{d.platform}:{d.id}"
    d = _CURRENT[0]._device
    return f"{d.platform}:{d.id}"


def get_default_device():
    return _CURRENT[0]._device if _CURRENT[0] is not None else jax.devices()[0]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def synchronize():
    # XLA is async; block on a trivial transfer to drain the stream.
    import jax.numpy as jnp

    jnp.zeros(()).block_until_ready()
