"""Eager op dispatch: wrap a jnp/lax function so it consumes/produces Tensors
and records a vjp closure on the tape.

This is the TPU-native replacement for the reference's generated
`xxx_ad_func()` C++ layer + PHI kernel dispatch (SURVEY.md §3.1 steps 2-3):
one generic `apply()` instead of 1000 generated bindings, because jax.vjp
derives every gradient.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import tape as _tape
from .tensor import Tensor


try:
    _typeof = jax.typeof
except AttributeError:  # jax < 0.6: typeof not exported; avals via core
    from jax.core import get_aval as _typeof


def _is_float(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating)


# AMP integration point: paddle_tpu.amp installs a lookup op_name -> dtype
# (or None) here when an auto_cast scope may be active. Kept as a hook so the
# hot eager path pays nothing when AMP was never imported.
_AMP_LOOKUP = None

# Static-graph integration point: paddle.enable_static() installs a handler
# (static/graph.py) that records ops touching symbolic placeholders into the
# current Program instead of executing them. None (the default) keeps the
# eager hot path untouched.
_STATIC_HANDLER = None


def set_amp_lookup(fn):
    global _AMP_LOOKUP
    _AMP_LOOKUP = fn


def set_static_handler(fn):
    global _STATIC_HANDLER
    _STATIC_HANDLER = fn


def amp_cast_arrays(arrays, jd):
    """The one AMP cast rule (shared by the eager autocast wrapper and the
    static meta-optimizer's program rewrite): real floats only — complex
    inputs must never be truncated to a real half dtype, and integers pass
    through untouched."""
    return [
        a.astype(jd)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != jd
        else a
        for a in arrays
    ]


def _maybe_amp_wrap(fn, op_name):
    if _AMP_LOOKUP is None:
        return fn
    jd = _AMP_LOOKUP(op_name)
    if jd is None:
        return fn

    def wrapped(*arrays, **kw):
        return fn(*amp_cast_arrays(arrays, jd), **kw)

    return wrapped


def apply(fn, *args, _op_name: str = "", **kwargs):
    """Run `fn(*arrays, **kwargs)` where Tensor args are unwrapped.

    If the tape is active and any input Tensor requires grad, the primal is
    computed through `jax.vjp` and the pullback recorded. Non-Tensor args
    pass through untouched (treated as constants).
    """
    fn = _maybe_amp_wrap(fn, _op_name)
    if _STATIC_HANDLER is not None:
        staged = _STATIC_HANDLER(fn, args, kwargs, _op_name)
        if staged is not None:
            return staged
    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    arrays = list(args)
    in_tensors = []
    for i in tensor_idx:
        in_tensors.append(args[i])
        arrays[i] = args[i]._data

    need_grad = (
        _tape.tape_enabled()
        and any(not t.stop_gradient for t in in_tensors)
    )

    if not need_grad:
        out = fn(*arrays, **kwargs)
        return _wrap_outputs(out, stop_gradient=True)

    # differentiate only w.r.t. floating-point tensor inputs
    diff_idx = [i for i in tensor_idx if _is_float(args[i]._data.dtype)]
    if not diff_idx:
        out = fn(*arrays, **kwargs)
        return _wrap_outputs(out, stop_gradient=True)

    def primal(*diff_arrays):
        full = list(arrays)
        for j, i in enumerate(diff_idx):
            full[i] = diff_arrays[j]
        return fn(*full, **kwargs)

    diff_data = [args[i]._data for i in diff_idx]
    out_data, vjp_fn = jax.vjp(primal, *diff_data)
    outs, structure = _flatten_out(out_data)
    out_tensors = [Tensor(o, stop_gradient=not _is_float(o.dtype)) for o in outs]
    diff_tensors = [args[i] for i in diff_idx]
    if any(not t.stop_gradient for t in out_tensors):
        _tape.global_tape().record(
            diff_tensors,
            out_tensors,
            _VjpAdapter(vjp_fn, [_typeof(o) for o in outs]),
            name=_op_name or getattr(fn, "__name__", "op"),
            replay=primal,
            in_data=diff_data,
        )
    return _unflatten_out(out_tensors, structure)


def _match_vma(ct, expected_aval):
    """Inside shard_map, primal outputs carry varying-manual-axes (vma) types
    (e.g. float32[...]{V:mp}); a cotangent built outside that op (ones_like,
    or the pullback of a replicating collective like psum) may be replicated.
    Promote it with pcast so jax.vjp accepts it — mathematically a no-op."""
    vma = getattr(expected_aval, "vma", None)
    if not vma:
        return ct
    have = getattr(_typeof(ct), "vma", frozenset())
    missing = tuple(vma - have)
    if missing:
        ct = jax.lax.pcast(ct, missing, to="varying")
    return ct


class _VjpAdapter:
    __slots__ = ("vjp_fn", "out_avals")

    def __init__(self, vjp_fn, out_avals):
        self.vjp_fn = vjp_fn
        self.out_avals = out_avals

    def __call__(self, cotangents):
        # cotangents: list aligned with flattened outputs
        cts = [_match_vma(ct, av) for ct, av in zip(cotangents, self.out_avals)]
        if len(self.out_avals) == 1:
            return self.vjp_fn(cts[0])
        return self.vjp_fn(tuple(cts))


def _out_type(out):
    # namedtuples (e.g. jnp.linalg results) collapse to plain tuple
    t = type(out)
    return tuple if hasattr(out, "_fields") else t


def _flatten_out(out):
    if isinstance(out, (tuple, list)):
        return list(out), _out_type(out)
    return [out], None


def _unflatten_out(tensors, structure):
    if structure is None:
        return tensors[0]
    return structure(tensors)


def _wrap_outputs(out, stop_gradient=True):
    if isinstance(out, (tuple, list)):
        return _out_type(out)(Tensor(o, stop_gradient=stop_gradient) for o in out)
    return Tensor(out, stop_gradient=stop_gradient)


def wrap_op(fn, name=None):
    """Lift a jnp-level function into a Tensor-level op."""

    @functools.wraps(fn)
    def op(*args, **kwargs):
        return apply(fn, *args, _op_name=name or fn.__name__, **kwargs)

    return op
