"""Tape-based autograd engine over JAX.

Design (TPU-first, not a port): the reference implements autograd as a C++
"eager" engine with generated GradNodes per op (ref layout:
paddle/fluid/eager/backward.cc, grad_node_info.h — upstream paths, see
SURVEY.md §2.1 N8). Here each eager op records a `jax.vjp` closure on a
Python tape instead. Because `jax.vjp` is itself traceable, the *same* tape
runs under `jax.jit`: tracing a whole train step (forward + `backward()` +
`optimizer.step()`) yields one fused XLA program — the role the reference's
dygraph-to-static + CINN stack plays (SURVEY.md §3.4), for free.
"""

from __future__ import annotations

import contextlib
import threading


import weakref


class TapeNode:
    """One recorded op: inputs, output ids/metadata, and a vjp closure."""

    __slots__ = (
        "inputs", "out_ids", "out_meta", "vjp_fn", "n_outputs", "idx", "name",
        "alive_outputs", "replay", "in_data",
    )

    def __init__(self, inputs, out_ids, out_meta, vjp_fn, n_outputs, idx,
                 name="", replay=None, in_data=None):
        self.inputs = inputs        # list[Tensor] (held strongly until the node is freed)
        self.out_ids = out_ids      # list[int] ids of output Tensors
        self.out_meta = out_meta    # list[(shape, dtype)] per output, for zero cotangents
        self.vjp_fn = vjp_fn        # cotangents(list) -> tuple of input cotangents
        self.n_outputs = n_outputs
        self.idx = idx              # monotonically increasing creation index
        self.name = name
        self.alive_outputs = n_outputs
        # replay(diff_arrays) -> primal out: re-linearization hook for
        # higher-order autograd — backward(create_graph=True) re-derives
        # this node's vjp AS A RECORDED OP of (inputs, cotangents), so the
        # produced gradients are themselves differentiable
        self.replay = replay
        # forward-time input arrays: replay must linearize at THESE, not at
        # whatever the input Tensors' ._data holds at backward time (an
        # in-place-style rebind between forward and backward would silently
        # shift the linearization point — advisor r4)
        self.in_data = in_data

    def _output_died(self):
        self.alive_outputs -= 1


class Tape:
    """A gradient tape. Nodes are kept in creation order; backward walks in reverse.

    Memory parity with the reference's refcounted GradNode graph: when every
    output Tensor of a node has been garbage-collected, no future backward can
    reach the node (cotangents are keyed by live output tensors), so it is
    pruned — this keeps grad-enabled inference loops from growing the tape
    without bound. Pruning is amortized on record().
    """

    _COMPACT_EVERY = 512

    def __init__(self):
        self.nodes = []
        self._counter = 0

    def record(self, inputs, outputs, vjp_fn, name="", replay=None,
               in_data=None):
        node = TapeNode(
            inputs=list(inputs),
            out_ids=[id(o) for o in outputs],
            out_meta=[(tuple(o._data.shape), o._data.dtype) for o in outputs],
            vjp_fn=vjp_fn,
            n_outputs=len(outputs),
            idx=self._counter,
            name=name,
            replay=replay,
            in_data=in_data,
        )
        self._counter += 1
        self.nodes.append(node)
        for o in outputs:
            o._tape_node = node
            weakref.finalize(o, node._output_died)
        if self._counter % self._COMPACT_EVERY == 0:
            self.compact()
        return node

    def compact(self):
        # iterate: dropping a dead node releases its input refs, which may
        # kill upstream outputs and let further nodes die in the next sweep
        while True:
            live = [n for n in self.nodes if n.alive_outputs > 0]
            if len(live) == len(self.nodes):
                break
            self.nodes = live


class _TapeState(threading.local):
    def __init__(self):
        self.tape = Tape()
        self.enabled = True
        self.depth = 0


_STATE = _TapeState()


def global_tape() -> Tape:
    return _STATE.tape


def tape_enabled() -> bool:
    return _STATE.enabled


def reset_tape():
    _STATE.tape = Tape()


@contextlib.contextmanager
def no_grad():
    """Paddle-parity `paddle.no_grad()`: ops inside are not recorded."""
    prev = _STATE.enabled
    _STATE.enabled = False
    try:
        yield
    finally:
        _STATE.enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _STATE.enabled
    _STATE.enabled = True
    try:
        yield
    finally:
        _STATE.enabled = prev


def is_grad_enabled() -> bool:
    return _STATE.enabled


@contextlib.contextmanager
def _grad_mode(mode: bool):
    prev = _STATE.enabled
    _STATE.enabled = bool(mode)
    try:
        yield
    finally:
        _STATE.enabled = prev


def set_grad_enabled(mode: bool):
    """Usable both as a statement and a context manager (paddle parity):
    the mode flips immediately; entering/exiting the returned context restores
    the caller's ORIGINAL mode afterwards."""
    prev = _STATE.enabled
    _STATE.enabled = bool(mode)

    @contextlib.contextmanager
    def _ctx():
        try:
            yield
        finally:
            _STATE.enabled = prev

    return _ctx()
