from .tensor import Tensor, Parameter, to_tensor
from .tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled, reset_tape, global_tape
from .autograd_engine import backward, grad
from . import dtype as dtypes
from .dtype import to_jax_dtype, get_default_dtype, set_default_dtype
from . import random as random_state
from .random import seed, get_rng_state_tracker
from . import device
from .flags import set_flags, get_flags, GLOBAL_FLAGS
from .op_call import apply, wrap_op
