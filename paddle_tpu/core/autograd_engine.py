"""Backward engine: reverse-creation-order walk over the tape.

Reference parity: `egr::Backward()`'s topological queue over GradNodes
(SURVEY.md §3.1 step 4; upstream paddle/fluid/eager/backward.cc). Here
creation order IS a topological order, so the walk is a single reversed scan —
no ready-queue bookkeeping needed. Fully traceable: running this under
`jax.jit` emits one XLA program for the whole backward pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import tape as _tape
from .tensor import Tensor, _GRAD_HOOKS, _GRAD_HOOK_OWNERS


def _zeros_like_meta(shape, dtype):
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def backward(loss: Tensor, grad_tensor=None, retain_graph: bool = False, targets=None):
    """Reverse walk from `loss`. `targets` (used by paddle.grad) is an optional
    set of tensor ids for which gradients must be materialized even when the
    tensor is an intermediate rather than a leaf."""
    if loss.stop_gradient:
        raise RuntimeError(
            "Tensor.backward() on a tensor with stop_gradient=True — nothing to differentiate."
        )
    targets = targets or {}
    tape = _tape.global_tape()
    start = loss._tape_node
    if start is None:
        if id(loss) in targets:
            t = targets[id(loss)]
            seed0 = jnp.ones(loss._data.shape, loss._data.dtype) if grad_tensor is None else (
                grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor))
            t.grad = Tensor(seed0) if t.grad is None else Tensor(t.grad._data + seed0)
        return

    if grad_tensor is None:
        seed = jnp.ones(loss._data.shape, loss._data.dtype)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # cotangents keyed by id(tensor)
    cot = {id(loss): seed}
    # keep loss alive and map ids we may need
    leaf_accum = {}  # id -> (tensor, grad array)

    if id(loss) in targets:
        t = targets[id(loss)]
        t.grad = Tensor(seed) if t.grad is None else Tensor(t.grad._data + seed)

    nodes = [n for n in tape.nodes if n.idx <= start.idx]
    with _tape.no_grad():
        for node in reversed(nodes):
            if not any(oid in cot for oid in node.out_ids):
                continue
            cots = []
            for oid, (shape, dtype) in zip(node.out_ids, node.out_meta):
                c = cot.pop(oid, None)
                if c is None:
                    c = _zeros_like_meta(shape, dtype)
                else:
                    for hook in _GRAD_HOOKS.get(oid, ()):  # intermediate-grad hooks
                        r = hook(Tensor(c))
                        if r is not None:
                            c = r._data if isinstance(r, Tensor) else jnp.asarray(r)
                    if oid in targets and oid != id(loss):
                        # materialize intermediate grads requested by paddle.grad
                        t = targets[oid]
                        t.grad = Tensor(c) if t.grad is None else Tensor(t.grad._data + c)
                cots.append(c)
            in_cots = node.vjp_fn(cots)
            for t, g in zip(node.inputs, in_cots):
                if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                    continue
                if t._tape_node is not None and t._tape_node.idx < node.idx:
                    # intermediate produced by an earlier node: keep propagating
                    tid = id(t)
                    cot[tid] = cot[tid] + g if tid in cot else g
                elif t._tape_node is None:
                    if not t.stop_gradient:
                        tid = id(t)
                        if tid in leaf_accum:
                            leaf_accum[tid] = (t, leaf_accum[tid][1] + g)
                        else:
                            leaf_accum[tid] = (t, g)
                else:
                    # t produced by this very node (in-place style) — treat as leaf
                    if not t.stop_gradient:
                        tid = id(t)
                        if tid in leaf_accum:
                            leaf_accum[tid] = (t, leaf_accum[tid][1] + g)
                        else:
                            leaf_accum[tid] = (t, g)

        for tid, (t, g) in leaf_accum.items():
            for hook in _GRAD_HOOKS.get(tid, ()):
                r = hook(Tensor(g))
                if r is not None:
                    g = r._data if isinstance(r, Tensor) else jnp.asarray(r)
            if t.grad is None:
                t.grad = Tensor(g, stop_gradient=True)
            else:
                t.grad._data = t.grad._data + g

    if not retain_graph:
        # free the graph (reference frees GradNodes after backward too)
        kept = [n for n in tape.nodes if n.idx > start.idx]
        tape.nodes = kept


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False, allow_unused=False):
    """paddle.grad parity (ref: python/paddle/autograd/ (U)) — functional form."""
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    saved = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    targets = {id(t): t for t in inputs}
    try:
        for i, o in enumerate(outputs):
            g = grad_outputs[i] if grad_outputs is not None else None
            backward(o, grad_tensor=g, retain_graph=True if retain_graph is None else retain_graph,
                     targets=targets)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; pass allow_unused=True."
                    )
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for t, g in saved:
            t.grad = g
