"""Backward engine: reverse-creation-order walk over the tape.

Reference parity: `egr::Backward()`'s topological queue over GradNodes
(SURVEY.md §3.1 step 4; upstream paddle/fluid/eager/backward.cc). Here
creation order IS a topological order, so the walk is a single reversed scan —
no ready-queue bookkeeping needed. Fully traceable: running this under
`jax.jit` emits one XLA program for the whole backward pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import tape as _tape
from .tensor import Tensor, _GRAD_HOOKS, _GRAD_HOOK_OWNERS


def _zeros_like_meta(shape, dtype):
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def backward(loss: Tensor, grad_tensor=None, retain_graph: bool = False,
             targets=None, create_graph: bool = False):
    """Reverse walk from `loss`. `targets` (used by paddle.grad) is an optional
    set of tensor ids for which gradients must be materialized even when the
    tensor is an intermediate rather than a leaf. With create_graph=True the
    walk RECORDS itself: each node's vjp is re-derived as a taped op of
    (original inputs, cotangents), so the produced gradients are themselves
    differentiable (higher-order autograd — ref eager backward's
    create_graph, SURVEY.md §2.1 N8)."""
    if loss.stop_gradient:
        raise RuntimeError(
            "Tensor.backward() on a tensor with stop_gradient=True — nothing to differentiate."
        )
    targets = targets or {}
    if create_graph:
        return _backward_tensors(loss, grad_tensor, targets)
    tape = _tape.global_tape()
    start = loss._tape_node
    if start is None:
        if id(loss) in targets:
            t = targets[id(loss)]
            seed0 = jnp.ones(loss._data.shape, loss._data.dtype) if grad_tensor is None else (
                grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor))
            t.grad = Tensor(seed0) if t.grad is None else Tensor(t.grad._data + seed0)
        return

    if grad_tensor is None:
        seed = jnp.ones(loss._data.shape, loss._data.dtype)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # cotangents keyed by id(tensor)
    cot = {id(loss): seed}
    # keep loss alive and map ids we may need
    leaf_accum = {}  # id -> (tensor, grad array)

    if id(loss) in targets:
        t = targets[id(loss)]
        t.grad = Tensor(seed) if t.grad is None else Tensor(t.grad._data + seed)

    nodes = [n for n in tape.nodes if n.idx <= start.idx]
    with _tape.no_grad():
        for node in reversed(nodes):
            if not any(oid in cot for oid in node.out_ids):
                continue
            cots = []
            for oid, (shape, dtype) in zip(node.out_ids, node.out_meta):
                c = cot.pop(oid, None)
                if c is None:
                    c = _zeros_like_meta(shape, dtype)
                else:
                    for hook in _GRAD_HOOKS.get(oid, ()):  # intermediate-grad hooks
                        r = hook(Tensor(c))
                        if r is not None:
                            c = r._data if isinstance(r, Tensor) else jnp.asarray(r)
                    if oid in targets and oid != id(loss):
                        # materialize intermediate grads requested by paddle.grad
                        t = targets[oid]
                        t.grad = Tensor(c) if t.grad is None else Tensor(t.grad._data + c)
                cots.append(c)
            in_cots = node.vjp_fn(cots)
            for t, g in zip(node.inputs, in_cots):
                if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                    continue
                if t._tape_node is not None and t._tape_node.idx < node.idx:
                    # intermediate produced by an earlier node: keep propagating
                    tid = id(t)
                    cot[tid] = cot[tid] + g if tid in cot else g
                elif t._tape_node is None:
                    if not t.stop_gradient:
                        tid = id(t)
                        if tid in leaf_accum:
                            leaf_accum[tid] = (t, leaf_accum[tid][1] + g)
                        else:
                            leaf_accum[tid] = (t, g)
                else:
                    # t produced by this very node (in-place style) — treat as leaf
                    if not t.stop_gradient:
                        tid = id(t)
                        if tid in leaf_accum:
                            leaf_accum[tid] = (t, leaf_accum[tid][1] + g)
                        else:
                            leaf_accum[tid] = (t, g)

        for tid, (t, g) in leaf_accum.items():
            for hook in _GRAD_HOOKS.get(tid, ()):
                r = hook(Tensor(g))
                if r is not None:
                    g = r._data if isinstance(r, Tensor) else jnp.asarray(r)
            if t.grad is None:
                t.grad = Tensor(g, stop_gradient=True)
            else:
                t.grad._data = t.grad._data + g

    if not retain_graph:
        # free the graph (reference frees GradNodes after backward too)
        kept = [n for n in tape.nodes if n.idx > start.idx]
        tape.nodes = kept


def _make_replay_bw(node):
    """Lift a node's backward into a re-recordable op: given the node's
    original diff inputs followed by the output cotangents, re-linearize
    the forward (node.replay) at those inputs and pull the cotangents
    back. Routed through op_call.apply, this records a tape node whose own
    vjp gives second-order gradients."""
    from .op_call import _match_vma, _typeof

    replay = node.replay
    k = len(node.inputs)

    def bw(*vals):
        prim = vals[:k]
        cots = list(vals[k:])
        out_data, vjp = jax.vjp(replay, *prim)
        flat = (list(out_data) if isinstance(out_data, (tuple, list))
                else [out_data])
        cts = [_match_vma(c, _typeof(o)) for c, o in zip(cots, flat)]
        res = vjp(cts[0]) if len(flat) == 1 else vjp(tuple(cts))
        # apply()'s convention: single outputs are bare, not 1-tuples
        # (_VjpAdapter keys its cotangent structure on that)
        return res[0] if len(res) == 1 else tuple(res)

    bw.__name__ = "grad_" + (node.name or "op")
    return bw


def _backward_tensors(loss: Tensor, grad_tensor, targets):
    """The create_graph walk: cotangents are live Tensors and every vjp
    application is itself a recorded op, so the resulting .grad tensors
    carry a tape history (differentiable). Implies retain_graph."""
    from . import op_call as _op_call

    tape = _tape.global_tape()
    start = loss._tape_node

    if grad_tensor is None:
        seed = Tensor(jnp.ones(loss._data.shape, loss._data.dtype),
                      stop_gradient=True)
    else:
        seed = (grad_tensor if isinstance(grad_tensor, Tensor)
                else Tensor(jnp.asarray(grad_tensor)))

    def accum_target(t, g):
        t.grad = g if t.grad is None else t.grad + g

    if start is None:
        if id(loss) in targets:
            accum_target(targets[id(loss)], seed)
        return
    if id(loss) in targets:
        accum_target(targets[id(loss)], seed)

    cot = {id(loss): seed}
    leaf_accum = {}
    nodes = [n for n in tape.nodes if n.idx <= start.idx]
    for node in reversed(nodes):
        if not any(oid in cot for oid in node.out_ids):
            continue
        if node.replay is None:
            raise NotImplementedError(
                f"create_graph=True through op {node.name!r}: this node "
                "has a custom backward (PyLayer) with no replayable "
                "forward, so its gradient cannot be differentiated again. "
                "Express the op with standard tensor ops, or use "
                "paddle.autograd.hessian/jvp (jax-transform based).")
        cots = []
        for oid, (shape, dtype) in zip(node.out_ids, node.out_meta):
            c = cot.pop(oid, None)
            if c is None:
                c = _zeros_like_meta(shape, dtype)
                if not isinstance(c, np.ndarray):   # float arrays -> Tensor
                    c = Tensor(c, stop_gradient=True)
            else:
                for hook in _GRAD_HOOKS.get(oid, ()):
                    r = hook(c)
                    if r is not None:
                        c = r if isinstance(r, Tensor) \
                            else Tensor(jnp.asarray(r))
                if oid in targets and oid != id(loss):
                    accum_target(targets[oid], c)
            cots.append(c)
        bw = _make_replay_bw(node)
        # replay must linearize at the FORWARD-time arrays: an input whose
        # ._data was rebound between forward and backward (in-place style)
        # is temporarily restored around the recorded bw apply, so the
        # linearization point matches the create_graph=False saved vjp
        # (advisor r4). Tracer-valued data stays — under an outer trace
        # the symbolic flow is the correct value.
        swapped = []
        if node.in_data is not None:
            for t, s in zip(node.inputs, node.in_data):
                if t._data is not s \
                        and not isinstance(t._data, jax.core.Tracer):
                    swapped.append((t, t._data))
                    t._data = s
        try:
            in_cots = _op_call.apply(bw, *(list(node.inputs) + cots),
                                     _op_name=bw.__name__)
        finally:
            for t, d in swapped:
                t._data = d
        if not isinstance(in_cots, (tuple, list)):
            in_cots = (in_cots,)
        for t, g in zip(node.inputs, in_cots):
            if g is None:
                continue
            gd = getattr(g, "_data", g)
            if hasattr(gd, "dtype") and gd.dtype == jax.dtypes.float0:
                continue
            if not isinstance(g, Tensor):
                g = Tensor(jnp.asarray(g))
            tid = id(t)
            if t._tape_node is not None and t._tape_node.idx < node.idx:
                cot[tid] = cot[tid] + g if tid in cot else g
            elif not t.stop_gradient:
                if tid in leaf_accum:
                    leaf_accum[tid] = (t, leaf_accum[tid][1] + g)
                else:
                    leaf_accum[tid] = (t, g)

    for tid, (t, g) in leaf_accum.items():
        for hook in _GRAD_HOOKS.get(tid, ()):
            r = hook(g)
            if r is not None:
                g = r if isinstance(r, Tensor) else Tensor(jnp.asarray(r))
        if t.grad is None:
            t.grad = g
        else:
            t.grad = t.grad + g
    # create_graph implies the graph stays (second backward needs it)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False, allow_unused=False):
    """paddle.grad parity (ref: python/paddle/autograd/ (U)) — functional form."""
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    saved = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    targets = {id(t): t for t in inputs}
    try:
        for i, o in enumerate(outputs):
            g = grad_outputs[i] if grad_outputs is not None else None
            backward(o, grad_tensor=g,
                     retain_graph=True if retain_graph is None else retain_graph,
                     targets=targets, create_graph=create_graph)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; pass allow_unused=True."
                    )
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for t, g in saved:
            t.grad = g
