"""The Tensor type: a dygraph-feel wrapper over `jax.Array`.

Reference parity: phi::DenseTensor + the Python Tensor bound via pybind
(SURVEY.md §2.1 N1/N24 — upstream paths paddle/phi/core/dense_tensor.cc,
paddle/fluid/pybind/eager_method.cc). TPU-native design: `_data` is always a
`jax.Array` (or a jax tracer under `jit`), so every Tensor method stays
traceable; autograd state (`grad`, `stop_gradient`, tape node) lives on the
Python wrapper, never in the compiled program.

Paddle semantics preserved:
  * tensors default to `stop_gradient=True`; `Parameter` flips it.
  * `t.backward()` populates `.grad` on every reachable leaf.
  * in-place mutators (`add_`, `set_value`, ...) rebind `_data`.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import tape as _tape


def _to_jax(value, dtype=None):
    if isinstance(value, Tensor):
        data = value._data
        return data.astype(dtype) if dtype is not None and data.dtype != dtype else data
    if isinstance(value, (jnp.ndarray, jax.Array)) or hasattr(value, "aval"):
        return value if dtype is None else value.astype(dtype)
    return jnp.asarray(value, dtype=dtype)


class Tensor:
    __slots__ = (
        "_data",
        "grad",
        "stop_gradient",
        "_tape_node",
        "name",
        "persistable",
        "trainable",
        "__weakref__",
    )

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        self._data = _to_jax(data, dtype)
        self.grad = None
        self.stop_gradient = stop_gradient
        self._tape_node = None
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient

    # ---------------- basic properties ----------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def T(self):
        from .. import tensor as ops

        return ops.transpose(self, list(range(self.ndim))[::-1])

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    @property
    def place(self):
        devs = getattr(self._data, "devices", None)
        try:
            return next(iter(devs())) if callable(devs) else None
        except Exception:
            return None

    # ---------------- conversion ----------------
    def numpy(self):
        return np.asarray(self._data)

    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def clone(self):
        from .op_call import apply

        return apply(lambda x: x + 0, self)

    def astype(self, dtype):
        from .op_call import apply
        from .dtype import to_jax_dtype

        jd = to_jax_dtype(dtype)
        return apply(lambda x: x.astype(jd), self)

    cast = astype

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    def ndimension(self):
        return self.ndim

    def element_size(self):
        return self._data.dtype.itemsize

    def to(self, *args, **kwargs):
        for a in args:
            if isinstance(a, (str, jnp.dtype, type(jnp.float32))) and not str(a).startswith(
                ("cpu", "gpu", "tpu", "xpu")
            ):
                try:
                    return self.astype(a)
                except Exception:
                    pass
        if "dtype" in kwargs and kwargs["dtype"] is not None:
            return self.astype(kwargs["dtype"])
        return self

    # ---------------- autograd ----------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .autograd_engine import backward as _backward

        _backward(self, grad_tensor=grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    @property
    def is_leaf(self):
        return self._tape_node is None

    def register_hook(self, hook):
        # Gradient hooks: stored on the tensor, applied by the backward engine.
        if not hasattr(self, "_grad_hooks"):
            pass
        hooks = _GRAD_HOOKS.setdefault(id(self), [])
        hooks.append(hook)
        _GRAD_HOOK_OWNERS[id(self)] = self
        class _Removable:
            def remove(_s):
                try:
                    hooks.remove(hook)
                except ValueError:
                    pass
        return _Removable()

    # ---------------- in-place / value ops ----------------
    def set_value(self, value):
        self._data = _to_jax(value, self._data.dtype)
        return self

    def copy_(self, other, *a):
        return self.set_value(other)

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def add_(self, y):
        self._data = self._data + _to_jax(y)
        return self

    def subtract_(self, y):
        self._data = self._data - _to_jax(y)
        return self

    def multiply_(self, y):
        self._data = self._data * _to_jax(y)
        return self

    def scale_(self, scale=1.0, bias=0.0):
        self._data = self._data * scale + bias
        return self

    def clip_(self, min=None, max=None):
        self._data = jnp.clip(self._data, min, max)
        return self

    def exp_(self):
        self._data = jnp.exp(self._data)
        return self

    def floor_(self):
        self._data = jnp.floor(self._data)
        return self

    def round_(self):
        self._data = jnp.round(self._data)
        return self

    def sqrt_(self):
        self._data = jnp.sqrt(self._data)
        return self

    def rsqrt_(self):
        self._data = 1.0 / jnp.sqrt(self._data)
        return self

    def reciprocal_(self):
        self._data = 1.0 / self._data
        return self

    def tanh_(self):
        self._data = jnp.tanh(self._data)
        return self

    def flatten_(self, start_axis=0, stop_axis=-1):
        nd = self._data.ndim
        s, e = start_axis % nd, stop_axis % nd
        shape = self._data.shape
        self._data = self._data.reshape(
            shape[:s] + (-1,) + shape[e + 1:])
        return self

    def squeeze_(self, axis=None):
        self._data = (jnp.squeeze(self._data) if axis is None
                      else jnp.squeeze(self._data, axis))
        return self

    def unsqueeze_(self, axis):
        self._data = jnp.expand_dims(self._data, axis)
        return self

    # ---------------- python protocol ----------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return f"Tensor(shape={self.shape}, dtype={self._data.dtype}{grad_info},\n       {self._data})"

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __getitem__(self, idx):
        from .op_call import apply

        idx = _index_to_jax(idx)
        return apply(lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _index_to_jax(idx)
        val = _to_jax(value)
        self._data = self._data.at[idx].set(val)

    # Arithmetic operators are attached by paddle_tpu.tensor (op namespaces) at
    # import time — mirroring how the reference monkey-patches math methods onto
    # the pybind Tensor (upstream python/paddle/tensor/math.py).


# grad hooks keyed by tensor id (kept out of __slots__ to keep Tensor small)
_GRAD_HOOKS: dict = {}
_GRAD_HOOK_OWNERS: dict = {}


def _index_to_jax(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


class Parameter(Tensor):
    """A trainable Tensor (stop_gradient=False), registered by nn.Layer."""

    __slots__ = ("optimize_attr", "regularizer", "is_distributed",
                 "_sharding_axes", "sequence_parallel", "no_weight_decay")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self._sharding_axes = None  # PartitionSpec-like hint used by auto-parallel
        self.sequence_parallel = False  # grads need an mp-allreduce (SP regions)
        self.no_weight_decay = False  # AdamW/coupled decay exemption flag

    def __repr__(self):
        return f"Parameter(name={self.name}, shape={self.shape}, dtype={self._data.dtype})\n       {self._data}"


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (ref: python/paddle/tensor/creation.py (U))."""
    if isinstance(data, Tensor) and dtype is None:
        t = Tensor(data._data, stop_gradient=stop_gradient)
        return t
    from .dtype import to_jax_dtype

    jd = to_jax_dtype(dtype) if dtype is not None else None
    if jd is None and isinstance(data, (int, bool, float)):
        # paddle defaults python floats to float32 (not float64)
        if isinstance(data, bool):
            jd = jnp.bool_
        elif isinstance(data, int):
            jd = jnp.int32
        else:
            jd = jnp.float32
    if jd is None and isinstance(data, (list, tuple)):
        arr = np.asarray(data)
        if arr.dtype == np.float64:
            jd = jnp.float32
        elif arr.dtype == np.int64:
            jd = jnp.int32
    return Tensor(data, dtype=jd, stop_gradient=stop_gradient)
