"""Dtype aliases with Paddle-style names (ref: paddle dtype enum in
paddle/phi/common/data_type.h (U)), mapped to jnp dtypes."""

import jax.numpy as jnp
import numpy as np

float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int": int32,
    "int64": int64,
    "long": int64,
    "uint8": uint8,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
}


def to_jax_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key.startswith("paddle."):
            key = key.split(".", 1)[1]
        if key not in _STR2DTYPE:
            raise ValueError(f"unknown dtype {dtype!r}")
        return _STR2DTYPE[key]
    return jnp.dtype(dtype)


_DEFAULT_DTYPE = [float32]


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def set_default_dtype(d):
    _DEFAULT_DTYPE[0] = to_jax_dtype(d)


def is_floating_point_dtype(d):
    return jnp.issubdtype(jnp.dtype(d), jnp.floating)
