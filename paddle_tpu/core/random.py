"""Stateful RNG over jax's functional PRNG.

Reference parity: paddle.seed + per-parallel-axis `get_rng_state_tracker`
(SURVEY.md §2.2 P12, upstream fleet/layers/mpu/random.py). TPU-native design:
a global counter-based key stream. Under `jax.jit` the key becomes a traced
argument (injected by paddle_tpu.jit.to_static) so compiled programs stay
stochastic across calls; named tracker states give deterministic, distinct
streams per parallelism axis (e.g. dropout that is identical across tensor-
parallel ranks vs. distinct per rank).
"""

from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp


class _KeyStream:
    """fold_in-counter key stream: cheap, traceable, replayable."""

    def __init__(self, seed_or_key):
        if isinstance(seed_or_key, int):
            self.base = jax.random.PRNGKey(seed_or_key)
        else:
            self.base = seed_or_key
        self.counter = 0

    def next_key(self):
        k = jax.random.fold_in(self.base, self.counter)
        self.counter += 1
        return k

    def state(self):
        return (self.base, self.counter)

    def set_state(self, st):
        self.base, self.counter = st


class _RandomState(threading.local):
    def __init__(self):
        self.stream = _KeyStream(0)


_STATE = _RandomState()


def seed(s: int):
    """paddle.seed parity."""
    _STATE.stream = _KeyStream(int(s))
    default_tracker().reset(int(s))
    return _STATE.stream


def next_key():
    return _STATE.stream.next_key()


def get_rng_state():
    return _STATE.stream.state()


def set_rng_state(st):
    _STATE.stream.set_state(st)


@contextlib.contextmanager
def fork_rng(base_key):
    """Swap the global stream for one derived from `base_key` (used by
    jit.to_static to thread a traced key through a compiled step)."""
    prev = _STATE.stream
    _STATE.stream = _KeyStream(base_key)
    try:
        yield
    finally:
        _STATE.stream = prev


class RNGStatesTracker:
    """Named RNG states for hybrid parallelism (parity with
    fleet get_rng_state_tracker: 'global_seed' vs 'local_seed' streams)."""

    def __init__(self):
        self.states = {}

    def reset(self, base_seed=0):
        self.states = {}
        self._base = base_seed

    def add(self, name, seed_):
        self.states[name] = _KeyStream(int(seed_))

    def get_states_tracker(self):
        return {k: v.state() for k, v in self.states.items()}

    def set_states_tracker(self, states):
        for k, st in states.items():
            self.states.setdefault(k, _KeyStream(0)).set_state(st)

    @contextlib.contextmanager
    def rng_state(self, name="global_seed"):
        if name not in self.states:
            self.add(name, hash(name) % (2**31))
        prev = _STATE.stream
        _STATE.stream = self.states[name]
        try:
            yield
        finally:
            _STATE.stream = prev


_TRACKER = RNGStatesTracker()


def default_tracker() -> RNGStatesTracker:
    return _TRACKER


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER
