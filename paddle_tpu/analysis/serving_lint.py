"""Serving thread-ownership & lock-discipline lint (analysis phase 2).

The serving fleet's correctness rests on doctrines PR 14 states as
prose; this pass makes them machine-checked, per file, without running
anything:

- **PTA510 engine ownership.**  One daemon thread per replica owns
  every mutating engine call (``submit/step/abort/drain/close/adopt``
  and mutations on the engine's ``pool``/``prefix`` store).  The lint
  rebuilds each class's intra-class call graph, roots it at every
  ``threading.Thread(target=self.X)`` entry, and flags mutating
  ``self.engine.*`` calls from methods OUTSIDE that worker-owned set —
  and mutating ``<other>.engine.*`` calls anywhere (another object's
  engine is never yours).  Local aliases (``eng = self.engine``) are
  tracked.  Ownership handoffs that are doctrine-sanctioned (closing
  an engine after ``drain()+stop()`` joined its thread) carry a
  justified ``# noqa: PTA510``.
- **PTA511 handle-lock atomicity.**  ``StreamHandle.request/worker/
  failing_over/abort_requested/failovers`` are rebound during failover
  under ``handle.lock``; writes outside a ``with <handle>.lock:``
  block race the supervisor's swap.  ``sent`` is deliberately NOT
  guarded — it is worker-thread-owned (flushed without the lock).
  Constructors (``__init__``) are pre-publication and exempt.
- **PTA512 blocking under a lock.**  ``queue.get()`` (argless or with
  a timeout), ``join`` on thread-ish receivers, ``adopt``/``drain``,
  ``Event.wait()``, and nonzero ``time.sleep`` inside a
  ``with ... lock:`` body can deadlock against the thread that needs
  the lock to make progress.  ``dict.get(key, default)`` (positional
  args) does not flag.
- **PTA513 wall clock in fault paths.**  Fault scheduling is keyed by
  dispatch ordinals so fault runs replay deterministically; inside
  fault/chaos/inject-named scopes, ``time.time/monotonic/
  perf_counter``, ``datetime.now``, and unseeded module-level
  ``random.*`` calls flag.  ``random.Random(seed)`` construction is
  the sanctioned pattern and does not.
- **PTA514 thread lifecycle.**  ``threading.Thread(...)`` without
  ``daemon=True`` flags unless the enclosing class (or module) joins a
  thread somewhere — the fleet pattern is daemon threads with explicit
  ``stop()`` joins.

Entry points: :func:`lint_source` / :func:`lint_file` (per-file, the
``--serving`` CLI path) and :func:`serving_check` (a live function or
class, source-mapped like ``analysis.check``).  All findings honor
``# noqa: PTA51x`` on the flagged line.
"""

from __future__ import annotations

import ast
import re
import textwrap

from .diagnostics import Diagnostic, make
from .trace_lint import _dotted, apply_noqa

__all__ = ["lint_source", "lint_file", "serving_check"]

#: engine methods the worker thread alone may call (reads like
#: ``.engine.stats()`` / ``.engine.scheduler.has_work`` stay free, and
#: ``install_faults`` is a GIL-atomic configuration store)
_ENGINE_MUTATORS = frozenset(
    {"submit", "step", "abort", "drain", "close", "adopt"})
#: mutating methods on the engine's pool / radix (prefix) store
_STORE_MUTATORS = frozenset(
    {"rebind", "reclaim", "insert", "adopt", "evict", "free",
     "allocate", "reset"})
#: StreamHandle attrs the failover swap rebinds under ``handle.lock``
#: (``sent`` is worker-thread-owned and deliberately absent)
_GUARDED_HANDLE_ATTRS = frozenset(
    {"request", "worker", "failing_over", "abort_requested",
     "failovers"})
#: names conventionally bound to StreamHandles in the gateway code
_HANDLE_NAMES = frozenset({"handle", "h", "stream_handle", "sh"})

_WALLCLOCK_CALLS = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow"})
_FAULT_SCOPE = re.compile(r"fault|chaos|inject", re.IGNORECASE)


def _last2(dotted):
    return dotted.split(".")[-2:] if dotted else []


def _is_thread_ctor(call):
    d = _dotted(call.func) or ""
    return d.split(".")[-1] == "Thread"


def _self_method_target(call):
    """'X' when a Thread(...) call has target=self.X, else None."""
    for kw in call.keywords:
        if kw.arg == "target" and isinstance(kw.value, ast.Attribute) \
                and isinstance(kw.value.value, ast.Name) \
                and kw.value.value.id in ("self", "cls"):
            return kw.value.attr
    return None


def _worker_owned_methods(cdef):
    """Methods of ``cdef`` that run on a thread the class itself
    started: every ``Thread(target=self.X)`` entry plus its same-class
    transitive callees (``self.m()`` edges)."""
    methods = {n.name: n for n in cdef.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    entries, edges = set(), {name: set() for name in methods}
    for name, fdef in methods.items():
        for node in ast.walk(fdef):
            if not isinstance(node, ast.Call):
                continue
            if _is_thread_ctor(node):
                tgt = _self_method_target(node)
                if tgt is not None:
                    entries.add(tgt)
            if isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in ("self", "cls") \
                    and node.func.attr in methods:
                edges[name].add(node.func.attr)
    owned, stack = set(), [e for e in entries if e in methods]
    while stack:
        m = stack.pop()
        if m in owned:
            continue
        owned.add(m)
        stack.extend(edges.get(m, ()))
    return owned


def _joins_anywhere(node):
    """True when the subtree contains a ``<x>.join(...)`` call — used
    to decide whether a non-daemon thread has a visible join path."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "join" \
                and not (n.args and isinstance(n.args[0], ast.Constant)
                         and isinstance(n.args[0].value, str)):
            return True
    return False


class _ServingLinter(ast.NodeVisitor):
    """One pass over a module tree; context (class / function / lock /
    engine-alias) is tracked on explicit stacks."""

    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        self._seen = set()
        self.class_stack = []        # (cdef, owned_methods, has_join)
        self.func_stack = []         # ast.FunctionDef
        self.lock_stack = []         # dotted lock owners ('self', 'handle')
        self.engine_aliases = []     # per-function set of local names
        self.module_has_join = False

    # -- emission ---------------------------------------------------------
    def emit(self, code, line, message=None):
        key = (code, line)
        if key not in self._seen:
            self._seen.add(key)
            self.diags.append(make(code, self.filename, line,
                                   message=message))

    # -- context ----------------------------------------------------------
    def visit_ClassDef(self, node):
        self.class_stack.append(
            (node, _worker_owned_methods(node), _joins_anywhere(node)))
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_FunctionDef(self, node):
        self.func_stack.append(node)
        self.engine_aliases.append(set())
        self.generic_visit(node)
        self.engine_aliases.pop()
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        held = []
        for item in node.items:
            d = _dotted(item.context_expr) or ""
            parts = d.split(".")
            if parts and parts[-1] in ("lock", "_lock"):
                owner = ".".join(parts[:-1])
                held.append(owner)
        for v in node.items:
            self.visit(v.context_expr)
        self.lock_stack.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held:
            self.lock_stack.pop()

    visit_AsyncWith = visit_With

    # -- assignments: handle-lock discipline + engine aliases -------------
    def _check_target(self, target, line):
        if not isinstance(target, ast.Attribute) \
                or target.attr not in _GUARDED_HANDLE_ATTRS:
            return
        root = target.value
        d = _dotted(root)
        if d is None or "." in d:
            return                    # only direct <handle>.<attr> writes
        in_handle_class = (
            d in ("self", "cls") and self.class_stack
            and self.class_stack[-1][0].name.endswith("Handle"))
        if d not in _HANDLE_NAMES and not in_handle_class:
            return
        if self.func_stack and self.func_stack[-1].name == "__init__":
            return                    # pre-publication construction
        if d in self.lock_stack:
            return                    # lexically under `with <d>.lock:`
        self.emit(
            "PTA511", line,
            message=f"StreamHandle state {d}.{target.attr!r} mutated "
                    f"outside `with {d}.lock` — races the failover swap")

    def visit_Assign(self, node):
        for t in node.targets:
            self._check_target(t, node.lineno)
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._check_target(e, node.lineno)
        # track `eng = <...>.engine` aliases for the ownership rule
        if self.engine_aliases and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            d = _dotted(node.value) or ""
            if d.split(".")[-1] == "engine":
                self.engine_aliases[-1].add(node.targets[0].id)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_target(node.target, node.lineno)
        self.generic_visit(node)

    # -- calls: ownership, blocking-under-lock, wall clock, threads -------
    def _in_fault_scope(self):
        if self.func_stack and _FAULT_SCOPE.search(
                self.func_stack[-1].name):
            return True
        return bool(self.class_stack and _FAULT_SCOPE.search(
            self.class_stack[-1][0].name))

    def _owned_here(self):
        """True when the current method runs on a thread its class
        started (the worker-owned call-graph set)."""
        if not self.class_stack or not self.func_stack:
            return False
        return self.func_stack[-1].name in self.class_stack[-1][1]

    def _check_engine_ownership(self, node, dotted):
        parts = dotted.split(".")
        method = parts[-1]
        aliases = self.engine_aliases[-1] if self.engine_aliases else set()
        # direct chains: <root>(...).engine.<mut>() / .engine.pool.<mut>()
        owner_is_self = parts[0] in ("self", "cls")
        alias_root = len(parts) == 2 and parts[0] in aliases
        if "engine" in parts[:-1]:
            eng_rel = parts[parts.index("engine") + 1:]
        elif alias_root:
            eng_rel = parts[1:]
        else:
            return
        flagged = None
        if len(eng_rel) == 1 and method in _ENGINE_MUTATORS:
            flagged = f"engine.{method}()"
        elif len(eng_rel) == 2 and eng_rel[0] in ("pool", "prefix") \
                and method in _STORE_MUTATORS:
            flagged = f"engine.{eng_rel[0]}.{method}()"
        if flagged is None:
            return
        if (owner_is_self or alias_root) and self._owned_here():
            return                    # on the thread that owns the engine
        where = (f"method {self.func_stack[-1].name!r}"
                 if self.func_stack else "module level")
        self.emit(
            "PTA510", node.lineno,
            message=f"{flagged} called from {where}, outside the "
                    "engine-owning worker thread"
                    + ("" if owner_is_self or alias_root
                       else " (another object's engine is never yours)"))

    def _check_blocking_under_lock(self, node, dotted):
        if not self.lock_stack:
            return
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else None
        has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
        if attr in ("get", "wait") and (not node.args or has_timeout):
            # argless .get()/.wait() is a queue/event block;
            # dict.get(key, default) passes positional args
            if not node.args:
                self.emit("PTA512", node.lineno,
                          message=f".{attr}() blocks while holding a "
                                  "lock")
        elif attr in ("adopt", "drain"):
            self.emit("PTA512", node.lineno,
                      message=f".{attr}() blocks on the worker inbox "
                              "while holding a lock")
        elif attr == "join" and (
                not node.args or has_timeout) and not (
                node.args and isinstance(node.args[0], ast.Constant)):
            recv = _dotted(f.value) or ""
            if not node.args or "thread" in recv.lower():
                self.emit("PTA512", node.lineno,
                          message=".join() blocks while holding a lock")
        elif dotted == "time.sleep":
            arg = node.args[0] if node.args else None
            if not (isinstance(arg, ast.Constant) and arg.value == 0):
                self.emit("PTA512", node.lineno,
                          message="time.sleep() while holding a lock")

    def _check_wallclock(self, node, dotted):
        if not self._in_fault_scope():
            return
        parts = dotted.split(".")
        if dotted in _WALLCLOCK_CALLS:
            self.emit("PTA513", node.lineno,
                      message=f"{dotted}() inside a fault-scheduling "
                              "path — schedule by dispatch ordinal")
        elif parts[0] in ("random", "np", "numpy") and "random" in parts \
                and parts[-1] != "Random":
            self.emit("PTA513", node.lineno,
                      message=f"unseeded {dotted}() inside a fault-"
                              "scheduling path — use random.Random(seed)")

    def _check_thread_ctor(self, node):
        if not _is_thread_ctor(node):
            return
        for kw in node.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return
        has_join = self.module_has_join if not self.class_stack \
            else self.class_stack[-1][2]
        if not has_join:
            self.emit("PTA514", node.lineno,
                      message="non-daemon Thread with no join/stop in "
                              "scope keeps the process alive at exit")

    def visit_Call(self, node):
        dotted = _dotted(node.func) or ""
        if dotted:
            self._check_engine_ownership(node, dotted)
            self._check_wallclock(node, dotted)
        self._check_blocking_under_lock(node, dotted)
        self._check_thread_ctor(node)
        self.generic_visit(node)


def lint_source(source, filename="<string>", line_offset=0):
    """Serving-doctrine lint of python source; returns [Diagnostic]
    sorted by line, with `# noqa` applied."""
    source = textwrap.dedent(source)
    tree = ast.parse(source)
    linter = _ServingLinter(filename)
    linter.module_has_join = _joins_anywhere(tree)
    linter.visit(tree)
    diags = apply_noqa(linter.diags, source)
    for d in diags:
        d.line += line_offset
    diags.sort(key=lambda d: (d.line, d.code))
    return diags


def lint_file(path):
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        return lint_source(src, filename=str(path))
    except SyntaxError as e:
        return [Diagnostic(code="PTA000", severity="error",
                           file=str(path), line=int(e.lineno or 0),
                           message=f"could not parse: {e.msg}", hint="")]


def serving_check(obj):
    """Lint a live function or class against the serving doctrines,
    with real file/line numbers (the programmatic peer of `check`)."""
    import inspect

    target = obj
    if inspect.ismethod(target):
        target = target.__func__
    try:
        src_lines, start = inspect.getsourcelines(target)
        srcfile = inspect.getsourcefile(target) or "<unknown>"
    except (OSError, TypeError):
        return []
    try:
        return lint_source("".join(src_lines), filename=srcfile,
                           line_offset=start - 1)
    except SyntaxError:
        return []
