"""AST trace-safety linter (ref: the validation/error layer around
python/paddle/jit/dy2static/ (U) — there unsupported constructs surface as
Dygraph2StaticException with source-mapped reports at TRANSLATION time;
here the same contract is checked WITHOUT running or tracing the function).

Two modes share one engine:

- **trace mode** (`paddle_tpu.analysis.check(fn)` / `to_static(...,
  check=True)`): every function in the source is assumed to run under
  trace; parameters are treated as possibly-traced values and the full
  rule set applies (PTA0xx unconvertible constructs, PTA1xx
  concretization, PTA2xx retrace, PTA3xx side effects).
- **package mode** (`python -m paddle_tpu.analysis <path>` / the repo
  self-lint gate): only functions decorated with `to_static` get the
  trace rules; every function gets the library self-lint rules (PTA401
  module-level jax.jit without static-arg annotation, PTA402
  tracer-leaking cache stores).

Taint is a deliberately simple forward dataflow: parameters start tainted
("possibly traced"), any name assigned from an expression that reads a
tainted name becomes tainted, literals stay clean. One-sided and
loop-carried flows are handled by a second body pass with (code, line)
dedup. False negatives are acceptable (it is a linter); false positives
are suppressible with `# noqa: PTA0xx` on the offending line.
"""

from __future__ import annotations

import ast
import builtins
import textwrap

from .diagnostics import Diagnostic, RULES, make, scan_statement

__all__ = ["check", "lint_source", "lint_file", "apply_noqa"]

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
_BUILTIN_NAMES = frozenset(dir(builtins))

_CONCRETIZE_METHODS = ("numpy", "item", "tolist")
_COERCE_FUNCS = ("int", "float", "bool")
_MUTATOR_METHODS = ("append", "extend", "insert", "add", "update",
                    "setdefault", "pop", "popitem", "remove", "clear")


def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_name(node):
    """The root ast.Name of an Attribute/Subscript/Call chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node if isinstance(node, ast.Name) else None


def _names_in(expr):
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _target_names(t, out):
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _target_names(e, out)
    elif isinstance(t, ast.Starred):
        _target_names(t.value, out)


def _local_bindings(fdef):
    """Every name the function body binds (params, assignments, loop
    targets, withitems, imports, nested defs) — used to distinguish local
    reads from global/closure reads. Nested scopes keep their own."""
    out = set()
    a = fdef.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        out.add(arg.arg)
    for v in (a.vararg, a.kwarg):
        if v is not None:
            out.add(v.arg)

    def walk(stmts):
        for node in stmts:
            if isinstance(node, _SCOPES):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    out.add(node.name)
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    _target_names(t, out)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                _target_names(node.target, out)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                _target_names(node.target, out)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        _target_names(item.optional_vars, out)
            elif isinstance(node, ast.Import):
                for al in node.names:
                    out.add((al.asname or al.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for al in node.names:
                    out.add(al.asname or al.name)
            for sub in ast.walk(node) if not isinstance(node, _SCOPES) \
                    else ():
                if isinstance(sub, ast.NamedExpr) \
                        and isinstance(sub.target, ast.Name):
                    out.add(sub.target.id)
                elif isinstance(sub, ast.ExceptHandler) and sub.name:
                    out.add(sub.name)
            for attr in ("body", "orelse", "finalbody"):
                child = getattr(node, attr, None)
                if child:
                    walk(child)
            for h in getattr(node, "handlers", ()) or ():
                walk(h.body)

    walk(fdef.body)
    return out


class _ModuleContext:
    """Per-file facts the function passes need: which module-level names
    are (probably) mutable containers, and which functions carry a
    to_static-ish decorator."""

    _MUTABLE_CALLS = ("list", "dict", "set", "bytearray", "OrderedDict",
                      "defaultdict", "deque", "Counter")

    def __init__(self, filename="<string>"):
        self.filename = filename
        self.mutable_globals = set()
        self.module_globals = set()

    @classmethod
    def from_tree(cls, tree, filename):
        ctx = cls(filename)
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            names = set()
            for t in targets:
                _target_names(t, names)
            ctx.module_globals |= names
            if isinstance(value, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(value, ast.Call)
                    and (_dotted(value.func) or "").split(".")[-1]
                    in cls._MUTABLE_CALLS):
                ctx.mutable_globals |= names
        return ctx

    @classmethod
    def from_globals(cls, glb, filename):
        ctx = cls(filename)
        for name, val in (glb or {}).items():
            ctx.module_globals.add(name)
            if isinstance(val, (list, dict, set, bytearray)):
                ctx.mutable_globals.add(name)
        return ctx


def _is_to_static_decorated(fdef):
    for dec in fdef.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = _dotted(target) or ""
        if dotted.split(".")[-1] == "to_static":
            return True
    return False


class _FunctionLinter:
    """Lints ONE function scope. Nested defs are linted by their own
    instances (driven from lint_source), so `self.fdef.body` statements
    are walked with nested scopes skipped."""

    def __init__(self, fdef, ctx, traced, diags):
        self.fdef = fdef
        self.ctx = ctx
        self.traced = traced
        self._sink = diags
        self._seen = set()
        a = fdef.args
        params = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
        for v in (a.vararg, a.kwarg):
            if v is not None:
                params.append(v.arg)
        self.self_names = {n for n in params[:1] if n in ("self", "cls")}
        self.params = set(params) - self.self_names
        self.tainted = set(self.params)
        self.locals = _local_bindings(fdef)
        self.global_decls = set()
        self.with_depth = 0
        self.cf_depth = 0
        self.iterfor_depth = 0
        self._flagged_globals = set()

    # -- emission ----------------------------------------------------------
    def emit(self, code, line, message=None):
        key = (code, line)
        if key in self._seen:
            return
        self._seen.add(key)
        self._sink.append(make(code, self.ctx.filename, line,
                               message=message))

    # -- taint -------------------------------------------------------------
    def is_tainted(self, expr):
        if expr is None:
            return False
        names = _names_in(expr)
        if names & self.tainted:
            return True
        # attribute reads off self are layer state (weights, buffers):
        # possibly traced
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id in self.self_names:
                return True
        return False

    def taint_target(self, t):
        names = set()
        _target_names(t, names)
        self.tainted |= names

    # -- driver ------------------------------------------------------------
    def run(self):
        fdef = self.fdef
        if self.traced:
            is_gen = isinstance(fdef, ast.AsyncFunctionDef) or any(
                isinstance(n, (ast.Yield, ast.YieldFrom, ast.Await))
                for n in ast.walk(fdef)
                if not isinstance(n, _SCOPES) or n is fdef)
            if is_gen:
                self.emit("PTA005", fdef.lineno)
        self.walk(fdef.body)

    def walk(self, stmts):
        for s in stmts:
            self.stmt(s)

    def stmt(self, s):
        if isinstance(s, _SCOPES):
            return
        m = getattr(self, "stmt_" + type(s).__name__, None)
        if m is not None:
            m(s)
            return
        for v in ast.iter_child_nodes(s):
            if isinstance(v, ast.expr):
                self.expr(v)
        for attr in ("body", "orelse", "finalbody"):
            child = getattr(s, attr, None)
            if child:
                self.walk(child)
        for h in getattr(s, "handlers", ()) or ():
            self.walk(h.body)

    # -- statements --------------------------------------------------------
    def stmt_Delete(self, s):
        if self.traced and self.cf_depth > 0:
            self.emit("PTA001", s.lineno)

    def stmt_Global(self, s):
        self.global_decls |= set(s.names)
        if self.traced and self.cf_depth > 0:
            self.emit("PTA002", s.lineno)

    def stmt_Nonlocal(self, s):
        if self.traced and self.cf_depth > 0:
            self.emit("PTA002", s.lineno)

    def stmt_Return(self, s):
        if self.traced and self.with_depth > 0:
            self.emit("PTA004", s.lineno)
        elif self.traced and self.iterfor_depth > 0:
            self.emit("PTA006", s.lineno)
        if s.value is not None:
            self.expr(s.value)

    def _exit(self, s):
        if self.traced and self.with_depth > 0:
            self.emit("PTA004", s.lineno)

    stmt_Break = _exit
    stmt_Continue = _exit

    def stmt_Assign(self, s):
        self.expr(s.value)
        tainted = self.is_tainted(s.value)
        for t in s.targets:
            self._check_store(t, s, tainted)
            if tainted:
                self.taint_target(t)

    def stmt_AugAssign(self, s):
        self.expr(s.value)
        tainted = self.is_tainted(s.value) or self.is_tainted(s.target)
        self._check_store(s.target, s, tainted)
        if tainted:
            self.taint_target(s.target)

    def stmt_AnnAssign(self, s):
        if s.value is None:
            return
        self.expr(s.value)
        tainted = self.is_tainted(s.value)
        self._check_store(s.target, s, tainted)
        if tainted:
            self.taint_target(s.target)

    def _check_store(self, target, s, tainted):
        # PTA301: attribute write on self/a parameter under trace
        if self.traced and isinstance(target, ast.Attribute):
            root = _root_name(target)
            if root is not None \
                    and root.id in (self.params | self.self_names):
                self.emit("PTA301", s.lineno)
        # PTA402 (any mode): subscript store into a module-level name of a
        # value derived from this function's arguments. Constant-index
        # slot writes (`_CONFIG[0] = x`) are module config registers, a
        # deliberate pattern — only keyed (cache-like) stores flag.
        if isinstance(target, ast.Subscript) \
                and not isinstance(target.slice, ast.Constant):
            root = _root_name(target)
            if root is not None and root.id not in self.locals \
                    and (root.id in self.ctx.module_globals
                         or root.id in self.global_decls) \
                    and tainted:
                self.emit("PTA402", s.lineno)

    def stmt_If(self, s):
        self._branch_test(s, s.test)
        self.cf_depth += 1
        self._walk_twice(s.body)
        self._walk_twice(s.orelse)
        self.cf_depth -= 1

    def stmt_While(self, s):
        self._branch_test(s, s.test)
        if s.orelse and self.traced:
            self.emit("PTA003", s.lineno)
        self.cf_depth += 1
        self._walk_twice(s.body)
        self.walk(s.orelse)
        self.cf_depth -= 1

    def stmt_For(self, s):
        self.expr(s.iter)
        if s.orelse and self.traced:
            self.emit("PTA003", s.lineno)
        if self.is_tainted(s.iter):
            self.taint_target(s.target)
        from .diagnostics import _is_range_call

        non_range = not _is_range_call(s.iter)
        self.cf_depth += 1
        if non_range:
            self.iterfor_depth += 1
        self._walk_twice(s.body)
        if non_range:
            self.iterfor_depth -= 1
        self.walk(s.orelse)
        self.cf_depth -= 1

    stmt_AsyncFor = stmt_For

    def stmt_With(self, s):
        for item in s.items:
            self.expr(item.context_expr)
            if item.optional_vars is not None \
                    and self.is_tainted(item.context_expr):
                self.taint_target(item.optional_vars)
        self.with_depth += 1
        self.walk(s.body)
        self.with_depth -= 1

    stmt_AsyncWith = stmt_With

    def stmt_Try(self, s):
        self.with_depth += 1
        self.walk(s.body)
        for h in s.handlers:
            self.walk(h.body)
        self.walk(s.orelse)
        self.walk(s.finalbody)
        self.with_depth -= 1

    def stmt_Expr(self, s):
        self.expr(s.value)

    def _walk_twice(self, stmts):
        """Second pass propagates loop-carried / cross-branch taint; the
        (code, line) dedup in emit() keeps diagnostics single."""
        if not stmts:
            return
        before = set(self.tainted)
        self.walk(stmts)
        if self.tainted != before:
            self.walk(stmts)

    def _branch_test(self, s, test):
        self.expr(test)
        if not self.traced:
            return
        if self.is_tainted(test):
            # PTA203: shape-dependent python branch (retrace per shape)
            for n in ast.walk(test):
                if isinstance(n, ast.Attribute) and n.attr == "shape" \
                        and self.is_tainted(n.value):
                    self.emit("PTA203", s.lineno)
                    break
            # PTA103 + the construct's own PTA0xx: a traced predicate on a
            # statement the converter refuses to stage fails at trace time
            reasons = scan_statement(s)
            if reasons:
                self.emit("PTA103", s.lineno)
                for code, line in reasons:
                    self.emit(code, line)

    # -- expressions -------------------------------------------------------
    def expr(self, e):
        if e is None or isinstance(e, _SCOPES):
            return
        for node in ast.walk(e):
            if isinstance(node, ast.Call):
                self._call(node)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                self._name_load(node)

    def _call(self, node):
        if not self.traced:
            return
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _CONCRETIZE_METHODS and self.is_tainted(f.value):
                self.emit("PTA101", node.lineno)
            if f.attr in _MUTATOR_METHODS:
                root = _root_name(f.value)
                if root is not None and root.id not in self.locals \
                        and root.id not in _BUILTIN_NAMES \
                        and not isinstance(f.value, ast.Attribute):
                    # container named by an outer (global/closure) binding
                    self.emit("PTA302", node.lineno)
                elif root is not None and root.id in self.self_names:
                    self.emit("PTA301", node.lineno)
            dotted = _dotted(f) or ""
            parts = dotted.split(".")
            if "random" in parts[:-1] or parts[0] == "random":
                # random.random(), np.random.*, numpy.random.*
                self.emit("PTA202", node.lineno)
        elif isinstance(f, ast.Name):
            if f.id in _COERCE_FUNCS and node.args \
                    and self.is_tainted(node.args[0]):
                self.emit("PTA102", node.lineno)

    def _name_load(self, node):
        if not self.traced:
            return
        nid = node.id
        if nid in self.locals or nid in _BUILTIN_NAMES:
            return
        if nid in self.ctx.mutable_globals \
                and nid not in self._flagged_globals:
            self._flagged_globals.add(nid)
            self.emit("PTA201", node.lineno,
                      message=f"mutable global {nid!r} read under trace "
                              "is captured as a compile-time constant")


# --------------------------------------------------------------------------
# module-level self-lint (package mode)


def _jit_call_missing_static(call):
    """True when `call` is jax.jit(...) / functools.partial(jax.jit, ...)
    with no static_argnums/static_argnames annotation."""
    dotted = _dotted(call.func) or ""
    kw = {k.arg for k in call.keywords}
    if dotted.split(".")[-1] == "partial" and call.args \
            and (_dotted(call.args[0]) or "").endswith("jax.jit"):
        return not (kw & {"static_argnums", "static_argnames"})
    if dotted == "jax.jit" or dotted.endswith(".jax.jit"):
        return not (kw & {"static_argnums", "static_argnames"})
    return False


def _lint_module_level(tree, ctx, diags):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    if _jit_call_missing_static(dec):
                        diags.append(make("PTA401", ctx.filename,
                                          dec.lineno))
                elif (_dotted(dec) or "") == "jax.jit":
                    diags.append(make("PTA401", ctx.filename, dec.lineno))
        elif isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Call) \
                    and _jit_call_missing_static(node.value):
                diags.append(make("PTA401", ctx.filename, node.lineno))


# --------------------------------------------------------------------------
# entry points


def _iter_functions(tree):
    """(fdef, enclosing_chain) for every def at any nesting depth."""
    stack = [(n, ()) for n in tree.body]
    while stack:
        node, chain = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, chain
            for child in node.body:
                stack.append((child, chain + (node,)))
        elif isinstance(node, ast.ClassDef):
            for child in node.body:
                stack.append((child, chain))
        else:
            for attr in ("body", "orelse", "finalbody"):
                for child in getattr(node, attr, None) or ():
                    stack.append((child, chain))
            for h in getattr(node, "handlers", ()) or ():
                for child in h.body:
                    stack.append((child, chain))


def apply_noqa(diags, source):
    """Honor `# noqa` / `# noqa: PTA001[,PTA002]` markers on the flagged
    line."""
    lines = source.splitlines()
    out = []
    for d in diags:
        if 1 <= d.line <= len(lines):
            line = lines[d.line - 1]
            idx = line.find("# noqa")
            if idx >= 0:
                rest = line[idx + len("# noqa"):]
                if not rest.lstrip().startswith(":"):
                    continue                      # bare noqa: drop all
                codes = rest.lstrip()[1:].replace(",", " ").split()
                if d.code in codes:
                    continue
        out.append(d)
    return out


def lint_source(source, filename="<string>", mode="trace",
                fn_globals=None, line_offset=0):
    """Lint python source. mode='trace' treats every function as traced;
    mode='package' applies trace rules only under to_static decorators and
    self-lint rules everywhere. Returns [Diagnostic] sorted by line."""
    source = textwrap.dedent(source)
    tree = ast.parse(source)
    if fn_globals is not None:
        ctx = _ModuleContext.from_globals(fn_globals, filename)
    else:
        ctx = _ModuleContext.from_tree(tree, filename)
    diags = []
    _lint_module_level(tree, ctx, diags)
    for fdef, chain in _iter_functions(tree):
        traced = (mode == "trace" or _is_to_static_decorated(fdef)
                  or any(_is_to_static_decorated(f) for f in chain))
        _FunctionLinter(fdef, ctx, traced, diags).run()
    diags = apply_noqa(diags, source)
    for d in diags:
        d.line += line_offset
    diags.sort(key=lambda d: (d.line, d.code))
    return diags


def lint_file(path, mode="package"):
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        return lint_source(src, filename=str(path), mode=mode)
    except SyntaxError as e:
        return [Diagnostic(code="PTA000", severity="error", file=str(path),
                           line=int(e.lineno or 0),
                           message=f"could not parse: {e.msg}", hint="")]


def check(fn):
    """Lint a function (or Layer / to_static-wrapped callable) WITHOUT
    running it. Returns [Diagnostic]; empty means no findings. The
    function's real file/line numbers are used, and its live globals feed
    the mutable-global capture rule (PTA201)."""
    import inspect

    target = fn
    # Layer -> its forward; StaticFunction and decorated wrappers unwrap
    fwd = getattr(target, "forward", None)
    if fwd is not None and not inspect.isfunction(target) \
            and not inspect.ismethod(target):
        target = fwd
    seen = set()
    while getattr(target, "__wrapped__", None) is not None \
            and id(target) not in seen:
        seen.add(id(target))
        target = target.__wrapped__
    inner = getattr(target, "_fn", None)        # StaticFunction
    if inner is not None and not inspect.isfunction(target):
        target = inner
    if isinstance(target, (staticmethod, classmethod)):
        target = target.__func__
    if inspect.ismethod(target):
        target = target.__func__
    if not (inspect.isfunction(target) or inspect.ismethod(target)):
        raise TypeError(
            f"analysis.check expects a function, method, Layer, or "
            f"to_static-wrapped callable, got {type(fn).__name__}")
    try:
        src_lines, src_start = inspect.getsourcelines(target)
        src = "".join(src_lines)
        srcfile = inspect.getsourcefile(target) or "<unknown>"
        line0 = src_start - 1
    except (OSError, TypeError):
        return []
    try:
        return lint_source(src, filename=srcfile, mode="trace",
                           fn_globals=getattr(target, "__globals__", None),
                           line_offset=line0)
    except SyntaxError:
        return []
