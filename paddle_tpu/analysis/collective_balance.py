"""Collective-balance checker (analysis phase 2): static rejection of
collective-comms bugs that are invisible on the CPU proxy.

Extends the PR 11 comms walker (``observability.comms``) from a
*census* into a *verifier*.  Everything is static — ``jax.make_jaxpr``
traces the program abstractly, no FLOPs run, no collective dispatches:

- **PTA701 branch balance.**  The branches of a ``lax.cond`` must
  issue identical ``(op, axis)`` collective censuses: on a real
  multi-chip mesh, ranks whose predicate picks the other branch stop
  participating and the collective deadlocks.  (jax itself permits
  this — the deadlock only materializes on real meshes.)
- **PTA702 unbounded-loop collectives.**  A collective inside a
  ``lax.while_loop`` body runs a data-dependent number of times; per-
  rank divergence deadlocks unless the predicate is replicated.  The
  comms walker's ``unbounded_loops`` flag, promoted to a finding with
  a source location.
- **PTA703 unbound axes.**  A collective over an axis name bound by no
  enclosing ``shard_map`` mesh and absent from the declared axis
  environment.  shard_map-aware (axes its mesh binds are fine even
  under ``lax.scan`` — the MeshEngine decode shape), so this agrees
  with the graph doctor's PTA505 instead of double-reporting.
- **PTA704 census drift.**  The statically-walked census is compared
  against a registered expected-census formula — the MULTICHIP decode
  gate (psum = L·h, all_gather = (3L+1)·h per dispatch) promoted from
  a bench assertion into a lint that runs without executing the
  program.  :func:`register_expected_census` holds the formulas;
  :func:`check_census` compares.

Findings carry the collective's real source location (jaxpr eqn source
info), so ``# noqa: PTA70x`` on the flagged source line suppresses
(via :func:`diagnostics.apply_noqa_files`).
"""

from __future__ import annotations

from .diagnostics import apply_noqa_files, make

__all__ = ["check_balance", "balance_jaxpr", "check_census",
           "register_expected_census", "expected_census_registry"]


def _comms():
    from ..observability import comms

    return comms


def _doctor():
    from . import graph_doctor

    return graph_doctor


def _census_of(jaxpr, bound_axes):
    """{(op, axis): calls} for one (sub-)jaxpr, scan-multiplied — the
    comparison key for branch balance.  Purely structural (no
    diagnostics)."""
    comms = _comms()
    doctor = _doctor()
    census = {}

    def walk(j, mult):
        for eqn in j.eqns:
            name = eqn.primitive.name
            canon = comms._PRIM_CANON.get(name)
            if canon is not None:
                for ax in doctor._axis_names(eqn.params):
                    key = (canon, ax)
                    census[key] = census.get(key, 0) + mult
                continue
            sub_mult = mult
            if name == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1) or 1)
            for sub in doctor._sub_jaxprs(eqn.params):
                walk(sub, sub_mult)

    walk(getattr(jaxpr, "jaxpr", jaxpr), 1)
    return census


def balance_jaxpr(closed_jaxpr, axis_sizes=None, file="<jaxpr>"):
    """Walk a (Closed)Jaxpr and return balance findings
    [Diagnostic]: PTA701 cond-branch imbalance, PTA702 collectives in
    data-dependent while loops, PTA703 axes bound by no enclosing
    shard_map mesh nor ``axis_sizes``."""
    comms = _comms()
    doctor = _doctor()
    diags = []

    def fmt(census):
        return {f"{op}@{ax}": n
                for (op, ax), n in sorted(census.items())} or {}

    def walk(j, bound):
        for eqn in j.eqns:
            name = eqn.primitive.name
            f = doctor._eqn_file(eqn, file)
            ln = doctor._eqn_line(eqn, 0)
            canon = comms._PRIM_CANON.get(name)
            if canon is not None:
                for ax in doctor._axis_names(eqn.params):
                    if ax not in bound:
                        diags.append(make(
                            "PTA703", f, ln,
                            message=f"collective {canon!r} runs over "
                                    f"axis {ax!r}, bound by no "
                                    "enclosing shard_map mesh (bound: "
                                    f"{sorted(bound)})"))
                continue
            sub_bound = bound
            if name == "cond":
                branches = list(doctor._sub_jaxprs(eqn.params))
                censuses = [_census_of(b, bound) for b in branches]
                if censuses and any(c != censuses[0]
                                    for c in censuses[1:]):
                    shown = [fmt(c) for c in censuses]
                    diags.append(make(
                        "PTA701", f, ln,
                        message="cond branches issue different "
                                f"collective censuses {shown} — ranks "
                                "taking different branches deadlock on "
                                "a real mesh"))
            elif name == "while":
                for sub in doctor._sub_jaxprs(eqn.params):
                    inner = _census_of(sub, bound)
                    if inner:
                        diags.append(make(
                            "PTA702", f, ln,
                            message="collectives "
                                    f"{fmt(inner)} inside a while loop "
                                    "run a data-dependent number of "
                                    "times — per-rank divergence "
                                    "deadlocks"))
                        break
            elif "shard_map" in name:
                mesh = eqn.params.get("mesh")
                if mesh is not None:
                    sub_bound = bound | set(
                        comms._mesh_axis_sizes(mesh))
            for sub in doctor._sub_jaxprs(eqn.params):
                walk(sub, sub_bound)

    walk(getattr(closed_jaxpr, "jaxpr", closed_jaxpr),
         set(axis_sizes or ()))
    diags.sort(key=lambda d: (d.file, d.line, d.code))
    return apply_noqa_files(diags)


def check_balance(fn, *args, axis_sizes=None, axis_env=None, **kwargs):
    """Trace ``fn(*args)`` abstractly and run :func:`balance_jaxpr`.
    ``axis_sizes``: {axis: size} bound OUTSIDE the traced program (its
    names also feed ``axis_env`` for tracing bare collectives)."""
    import jax

    env = axis_env
    if env is None and axis_sizes:
        env = [(name, int(size)) for name, size in axis_sizes.items()]
    closed = jax.make_jaxpr(fn, axis_env=env or None)(*args, **kwargs)
    code = getattr(fn, "__code__", None)
    file = code.co_filename if code is not None else "<jaxpr>"
    return balance_jaxpr(closed, axis_sizes=axis_sizes, file=file)


# --------------------------------------------------------------------------
# census drift (PTA704)

#: name -> callable(**params) returning the expected {(op, axis): calls}
#: census — the registered hand-derived formulas programs are gated on
expected_census_registry = {}


def register_expected_census(name, formula):
    """Register a hand-derived census formula (callable returning
    {(op, axis): calls}) under ``name`` — e.g. the MULTICHIP decode
    census psum=L*h / all_gather=(3L+1)*h.  Returns ``formula`` so it
    can be used as a decorator."""
    # not a trace-time cache: registration happens at import/setup time
    # with concrete callables — no tracer can reach this store
    expected_census_registry[name] = formula  # noqa: PTA402
    return formula


def check_census(fn, args=(), expected=None, *, name=None,
                 axis_sizes=None, formula_kwargs=None, file=None):
    """Statically verify that ``fn(*args)``'s collective census matches
    ``expected`` ({(op, axis): calls}) or the registered formula
    ``name`` called with ``formula_kwargs``.  The census is computed by
    the PR 11 comms walker (``observability.comms.analyze_jaxpr``) on
    an abstract trace — the program is never executed.  Returns
    [Diagnostic] — empty means the census holds exactly."""
    import jax

    if expected is None:
        if name is None or name not in expected_census_registry:
            raise ValueError(
                "check_census needs `expected` or a registered formula "
                f"`name` (known: {sorted(expected_census_registry)})")
        expected = expected_census_registry[name](
            **(formula_kwargs or {}))
    env = [(ax, int(sz)) for ax, sz in (axis_sizes or {}).items()]
    closed = jax.make_jaxpr(fn, axis_env=env or None)(*args)
    got = _comms().analyze_jaxpr(closed,
                                 axis_sizes=axis_sizes).counts()
    if got == dict(expected):
        return []
    code = getattr(fn, "__code__", None)
    f = file or (code.co_filename if code is not None else "<jaxpr>")
    line = code.co_firstlineno if code is not None else 0

    def fmt(census):
        return {f"{op}@{ax}": n
                for (op, ax), n in sorted(census.items())}

    diags = [make(
        "PTA704", f, line,
        message=f"collective census drift: program issues {fmt(got)}, "
                f"the registered formula expects {fmt(dict(expected))}")]
    return apply_noqa_files(diags)
