"""Structured diagnostics shared by the trace-safety linter, the graph
doctor, and the dy2static converter's runtime errors (ref: the ErrorData /
error-report machinery in python/paddle/jit/dy2static/error.py (U) — there a
runtime failure inside translated code is re-raised with the ORIGINAL
dygraph source location and a suggestion; here the same structured record
{code, severity, file, line, message, hint} backs three surfaces: the
pre-trace linter, the post-build graph doctor, and the converter's
"deliberately NOT converted" runtime error, so the CLI and the runtime tell
one story).

Rule codes are stable identifiers (PTA = Paddle-Tpu Analysis):

- PTA0xx  constructs the dy2static converter deliberately does not stage
          (the machine-checked form of the `jit/dy2static.py` docstring
          contract)
- PTA1xx  concretization hazards (host-value reads of possibly-traced data)
- PTA2xx  retrace hazards (per-step recompilation / stale captures)
- PTA3xx  side effects under trace (mutations the staged program drops)
- PTA4xx  repo-facing self-lint rules for library code
- PTA5xx  graph-doctor findings on a recorded Program / traced jaxpr
          (PTA501-505) and the serving thread-ownership / lock-discipline
          lint (PTA510-514, serving_lint.py)
- PTA6xx  donation-discipline findings (donation_doctor.py): use-after-
          donate, double donation, donated state escaping rebind
- PTA7xx  collective-balance findings (collective_balance.py): branch-
          unbalanced collectives, unbounded-loop collectives, unbound
          axes, census drift
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

__all__ = ["Diagnostic", "Rule", "RULES", "TraceSafetyWarning",
           "ERROR", "WARNING", "INFO", "scan_statement",
           "apply_noqa_files"]

ERROR = "error"
WARNING = "warning"
INFO = "info"


class TraceSafetyWarning(UserWarning):
    """Emitted by `to_static(..., check=True)` at decoration time."""


@dataclass(frozen=True)
class Rule:
    code: str
    severity: str
    title: str
    hint: str
    # which upstream dy2static/program-validation error this rule mirrors
    # (surfaced in docs/PARITY.md)
    mirrors: str = ""


@dataclass
class Diagnostic:
    code: str
    severity: str
    file: str
    line: int
    message: str
    hint: str = ""

    def format(self, with_hint=True):
        s = f"{self.file}:{self.line}: {self.code} {self.severity}: " \
            f"{self.message}"
        if with_hint and self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def __str__(self):
        return self.format()


_RULE_LIST = [
    # ---- PTA0xx: the converter's "deliberately NOT converted" contract
    Rule("PTA001", WARNING,
         "`del` inside a convertible control-flow body",
         "the if/while stays plain Python: fine for concrete predicates, "
         "but a traced tensor predicate will fail at run time — hoist the "
         "`del` out of the branch/loop body",
         mirrors="dy2static ifelse_transformer unsupported-stmt fallback"),
    Rule("PTA002", WARNING,
         "`global`/`nonlocal` declaration inside a convertible "
         "control-flow body",
         "staged branches carry assigned names as explicit dataflow; "
         "declare the name outside the if/while and assign through a local",
         mirrors="dy2static create_nonlocal_stmts limitation"),
    Rule("PTA003", WARNING,
         "`while/else` / `for/else` is never staged",
         "the else clause has no lax equivalent — restructure as a flag "
         "checked after the loop",
         mirrors="dy2static loop_transformer (no else-clause support)"),
    Rule("PTA004", WARNING,
         "early exit (`return`/`break`/`continue`) inside `with`/`try`",
         "the early-exit rewrite cannot guard statements across a context "
         "manager or exception handler — move the exit out of the "
         "with/try block",
         mirrors="dy2static return_transformer unsupported placement"),
    Rule("PTA005", ERROR,
         "generator/coroutine passed to to_static",
         "yield/await cannot be staged into one XLA program; make the "
         "function return whole tensors (e.g. a stacked scan output)",
         mirrors="dy2static convert_call generator passthrough"),
    Rule("PTA006", WARNING,
         "`return` inside a non-range `for` loop",
         "only `for i in range(...)` (and `for x in <tensor>`) loops get "
         "the early-exit rewrite — iterate by index or restructure",
         mirrors="dy2static break_continue_transformer scope limits"),
    Rule("PTA007", WARNING,
         "early exit the staging rewrite cannot reach",
         "this return/break/continue survives the early-exit rewrite, so "
         "the enclosing statement stays plain Python and fails for traced "
         "predicates — simplify the exit structure",
         mirrors="dy2static return_transformer fallback"),
    # ---- PTA1xx: concretization hazards
    Rule("PTA101", WARNING,
         "concretization: host read of a possibly-traced value",
         ".numpy()/.item()/.tolist() force a device sync and raise under "
         "jit tracing — keep the computation in tensor ops, or move the "
         "host read outside the traced function",
         mirrors="Variable.numpy() restriction under @to_static"),
    Rule("PTA102", WARNING,
         "concretization: int()/float()/bool() on a possibly-traced value",
         "Python scalar coercion needs a concrete value and raises a "
         "TracerError under jit — use tensor ops (astype/cast, comparisons) "
         "instead",
         mirrors="dy2static convert_var_dtype"),
    Rule("PTA103", ERROR,
         "tensor-dependent branch in a scope the converter cannot stage",
         "this if/while predicate depends on traced data but the statement "
         "contains an unconvertible construct, so it will raise at trace "
         "time — fix the construct or keep the predicate concrete",
         mirrors="dy2static ifelse_transformer + error.py report"),
    # ---- PTA2xx: retrace hazards
    Rule("PTA201", WARNING,
         "mutable global read under trace",
         "the value is captured as a compile-time constant: later mutations "
         "are silently ignored by cached traces — pass it as an argument "
         "or make it an immutable constant",
         mirrors="ProgramCache keyed on function + input signature"),
    Rule("PTA202", WARNING,
         "Python-side RNG under trace",
         "random()/np.random draw ONCE at trace time and bake the value "
         "into the compiled program — use paddle.rand/randn (traced, keyed "
         "RNG) instead",
         mirrors="dygraph-vs-static RNG divergence (seed program ops)"),
    Rule("PTA203", INFO,
         "shape-dependent Python branching",
         "branching on .shape specializes the trace: every new input shape "
         "recompiles — pad to fixed shapes or mark the dim dynamic in "
         "InputSpec",
         mirrors="to_static input_spec re-trace policy"),
    # ---- PTA3xx: side effects under trace
    Rule("PTA301", WARNING,
         "mutation of module/self state under trace",
         "attribute writes on the layer run at TRACE time, not per step; "
         "buffers must flow through return values (or register_buffer) to "
         "update inside the compiled program",
         mirrors="dy2static convert_attr / parameter write-back rules"),
    Rule("PTA302", WARNING,
         "mutation of an outer container under trace",
         "append/update on a closure or global container runs once at "
         "trace time (and leaks tracers out of the trace) — accumulate in "
         "a local and return it",
         mirrors="dy2static list_transformer (tensor-array conversion)"),
    # ---- PTA4xx: repo-facing self-lint
    Rule("PTA401", ERROR,
         "module-level jax.jit without static-arg annotation",
         "a jit created at import time hashes every non-array argument by "
         "value on each call; annotate static_argnums/static_argnames (or "
         "build the jit inside the function where config rides the "
         "closure)",
         mirrors="to_static input_spec contract"),
    Rule("PTA402", ERROR,
         "possibly tracer-leaking store into a module-level cache",
         "storing an argument-derived value into module state from inside "
         "potentially-traced code can leak tracers across traces; key "
         "caches on concrete metadata only, or suppress with `# noqa: "
         "PTA402` after verifying only concrete values reach this line",
         mirrors="ProgramCache lifetime rules"),
    # ---- PTA5xx: graph doctor
    Rule("PTA501", WARNING,
         "dead node: recorded op unreachable from any fetch",
         "the op was recorded into the Program (or traced into the jaxpr) "
         "but no fetch depends on it — dead compute is compiled and "
         "executed for effects-free ops by the reference executor; remove "
         "it or fetch its output",
         mirrors="Program prune/garbage-collection pass"),
    Rule("PTA502", WARNING,
         "unused feed: placeholder/input never consumed",
         "the feed is declared but no fetched value depends on it — drop "
         "the placeholder or wire it into the graph",
         mirrors="Executor feed/fetch validation"),
    Rule("PTA503", WARNING,
         "silent dtype widening",
         "a low-precision operand (bf16/f16) is silently promoted to f32+ "
         "(or f32 to f64 under x64): the op runs at the wide dtype and the "
         "memory/speed benefit of the narrow dtype is lost — cast "
         "explicitly or align operand dtypes",
         mirrors="AMP o2 white/black-list promotion checks"),
    Rule("PTA504", WARNING,
         "host-callback/sync point inside the compiled program",
         "a host callback serializes the device pipeline every step — "
         "replace debug callbacks/py callbacks with traced ops, or hoist "
         "them out of the hot program",
         mirrors="InterpreterCore D2H sync detection"),
    Rule("PTA505", ERROR,
         "collective over a mesh axis that is not bound",
         "the program psums/gathers over an axis name absent from the "
         "device mesh — it will fail (or silently no-op) at dispatch; "
         "check fleet topology axis names ('dp','pp','sharding','sep',"
         "'mp')",
         mirrors="ProcessGroup ring-id validation on c_* ops"),
    # ---- PTA51x: serving thread-ownership & lock-discipline lint
    Rule("PTA510", ERROR,
         "engine mutation outside the owning worker thread",
         "submit/step/abort/drain/close/adopt on an Engine (or its pool/"
         "radix store) must run on the worker thread that owns it — the "
         "thread-ownership doctrine: closing a live-threaded engine "
         "segfaults through donated buffers.  Route the call through the "
         "worker's command inbox, or suppress with `# noqa: PTA510` where "
         "ownership was provably transferred (post drain+stop)",
         mirrors="gateway EngineWorker ownership doctrine (PR 14)"),
    Rule("PTA511", ERROR,
         "StreamHandle state mutated outside `with handle.lock`",
         "request/worker/failing_over/abort_requested/failovers are "
         "rebound during failover under the handle lock; a bare write "
         "races the supervisor's swap — wrap the mutation in "
         "`with handle.lock:`",
         mirrors="StreamHandle failover-swap atomicity (PR 14)"),
    Rule("PTA512", WARNING,
         "blocking call while holding a lock",
         "queue.get()/join()/adopt()/drain()/sleep() under a held lock "
         "can deadlock against the thread that needs the lock to make "
         "progress — move the blocking wait outside the `with ... lock:` "
         "block",
         mirrors="EngineWorker inbox protocol (commands block OUTSIDE "
                 "handle locks)"),
    Rule("PTA513", WARNING,
         "wall-clock read inside a fault-scheduling path",
         "fault injection schedules by dispatch ordinal, never wall "
         "clock, so fault runs replay deterministically — derive timing "
         "from site-visit ordinals (FaultPlan) or seeded hashes "
         "(RetryPolicy.delay), not time.time()/monotonic()/unseeded "
         "random",
         mirrors="dispatch-ordinal fault doctrine (PR 14 FaultPlan)"),
    Rule("PTA514", WARNING,
         "non-daemon thread with no visible join/stop",
         "a non-daemon thread without a paired join keeps the process "
         "alive after main exits; pass daemon=True (the fleet pattern) "
         "or join it in a stop()/shutdown() path",
         mirrors="gateway/telemetry daemon-thread lifecycle pattern"),
    # ---- PTA6xx: donation doctor
    Rule("PTA601", ERROR,
         "use after donate: donated buffer read after dispatch",
         "an argument donated to a compiled function is invalidated by "
         "the dispatch; reading the host reference afterwards returns "
         "deleted-buffer errors (or garbage on some backends) — rebind "
         "the name from the call's outputs before any further use",
         mirrors="jax donated-buffer invalidation / engine state-rebind "
                 "discipline"),
    Rule("PTA602", ERROR,
         "double donation of one buffer",
         "the same argument position (or the same expression in two "
         "donated positions) is donated twice — XLA cannot alias one "
         "input into two outputs; deduplicate donate_argnums or pass "
         "distinct buffers",
         mirrors="XLA input-output aliasing validation"),
    Rule("PTA603", ERROR,
         "donated engine state escapes the rebind discipline",
         "a donated `self.*` buffer is not rebound from the call's "
         "outputs (directly or via a rebind method on its owner) before "
         "the function returns — live engine state now points at a "
         "donated buffer, the documented segfault class; rebind it "
         "immediately after the dispatch",
         mirrors="Engine._dispatch_decode pool.rebind discipline"),
    Rule("PTA604", WARNING,
         "wasted donation: no output matches the donated buffer",
         "the donated input's shape/dtype matches no program output, so "
         "XLA cannot reuse the buffer and the donation only invalidates "
         "the host reference — drop the argnum or thread the buffer "
         "through the outputs",
         mirrors="XLA donation fallback warning"),
    # ---- PTA7xx: collective-balance checker
    Rule("PTA701", ERROR,
         "collectives unbalanced across cond branches",
         "the branches of a `lax.cond` issue different collective "
         "censuses; on a real multi-chip mesh the ranks that take the "
         "other branch stop participating and the collective deadlocks "
         "(invisible on the CPU proxy) — issue the same collectives in "
         "every branch (reduce a zero if needed)",
         mirrors="MULTICHIP cond-balance deadlock class"),
    Rule("PTA702", WARNING,
         "collective inside a data-dependent while loop",
         "the loop's trip count is data-dependent, so per-rank collective "
         "counts can diverge and deadlock unless the predicate is "
         "replicated — prefer a bounded scan, or prove the predicate is "
         "identical on every rank",
         mirrors="comms walker unbounded_loops flag (PR 11)"),
    Rule("PTA703", ERROR,
         "collective over an axis unbound in the enclosing mesh",
         "no enclosing shard_map (or declared axis environment) binds "
         "this axis name — the dispatch will fail, or silently no-op "
         "under an unrelated binding; check the mesh axis names "
         "('dp','tp')",
         mirrors="graph doctor PTA505, shard_map-aware"),
    Rule("PTA704", ERROR,
         "collective census drift from the registered formula",
         "the program's statically-walked collective census no longer "
         "matches the registered expected-census formula (e.g. MULTICHIP "
         "decode: psum=L*h, all_gather=(3L+1)*h per dispatch) — either "
         "the program grew/lost a collective (fix it) or the formula is "
         "stale (update it WITH the derivation)",
         mirrors="MULTICHIP decode census exact gate (PR 13)"),
]

RULES = {r.code: r for r in _RULE_LIST}


def make(code, file, line, message=None, severity=None, hint=None):
    """Build a Diagnostic from the registry, with optional overrides."""
    r = RULES[code]
    return Diagnostic(code=code, severity=severity or r.severity,
                      file=file, line=int(line),
                      message=message or r.title, hint=hint or r.hint)


def apply_noqa_files(diags):
    """Honor `# noqa` markers for diagnostics whose ``file`` is a real,
    readable source file (the jaxpr-level analyzers map findings back to
    user source via eqn source info; the AST linters apply noqa against
    the in-memory source instead).  Unreadable files pass through."""
    cache = {}
    out = []
    for d in diags:
        lines = cache.get(d.file)
        if lines is None:
            try:
                with open(d.file, "r", encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = ()
            cache[d.file] = lines
        if 1 <= d.line <= len(lines):
            line = lines[d.line - 1]
            idx = line.find("# noqa")
            if idx >= 0:
                rest = line[idx + len("# noqa"):]
                if not rest.lstrip().startswith(":"):
                    continue
                codes = rest.lstrip()[1:].replace(",", " ").split()
                if d.code in codes:
                    continue
        out.append(d)
    return out


# --------------------------------------------------------------------------
# The "deliberately NOT converted" contract of jit/dy2static.py as a
# machine-checked classifier. `scan_statement` reports, for ONE if/while/for
# statement, every reason the converter will leave it as plain Python —
# used by the linter (PTA0xx findings) and by the converter itself to cite
# the matching code in its runtime error.

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _is_range_call(it):
    return (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range" and not it.keywords
            and 1 <= len(it.args) <= 3
            and not any(isinstance(a, ast.Starred) for a in it.args))


def scan_statement(node, include_plain_exits=False):
    """Reasons `node` (an ast.If / ast.While / ast.For) cannot be staged,
    as [(code, lineno)] in source order. With include_plain_exits, a bare
    return/break/continue remaining in the body (i.e. one the early-exit
    rewrite did not consume) reports as PTA007 — the converter uses that
    form; the linter does not (plain exits normally DO stage)."""
    out = []
    if isinstance(node, (ast.While, ast.For, ast.AsyncFor)) and node.orelse:
        out.append(("PTA003", node.lineno))

    def walk(stmts, in_with, loop_stack):
        for s in stmts:
            if isinstance(s, _SCOPES):
                continue
            if isinstance(s, ast.Delete):
                out.append(("PTA001", s.lineno))
            elif isinstance(s, (ast.Global, ast.Nonlocal)):
                out.append(("PTA002", s.lineno))
            elif isinstance(s, (ast.Return, ast.Break, ast.Continue)):
                if in_with:
                    out.append(("PTA004", s.lineno))
                elif isinstance(s, ast.Return) and "iter" in loop_stack:
                    out.append(("PTA006", s.lineno))
                elif isinstance(s, (ast.Break, ast.Continue)) \
                        and loop_stack:
                    pass        # belongs to the inner loop's own rewrite
                elif include_plain_exits:
                    out.append(("PTA007", s.lineno))
            if isinstance(s, (ast.With, ast.AsyncWith)):
                walk(s.body, True, loop_stack)
            elif isinstance(s, ast.Try):
                for blk in (s.body, s.orelse, s.finalbody):
                    walk(blk, True, loop_stack)
                for h in s.handlers:
                    walk(h.body, True, loop_stack)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                if s.orelse:
                    out.append(("PTA003", s.lineno))
                kind = ("range" if isinstance(s, ast.For)
                        and _is_range_call(s.iter) else "iter")
                walk(s.body, in_with, loop_stack + [kind])
                walk(s.orelse, in_with, loop_stack)
            elif isinstance(s, ast.While):
                if s.orelse:
                    out.append(("PTA003", s.lineno))
                walk(s.body, in_with, loop_stack + ["while"])
                walk(s.orelse, in_with, loop_stack)
            elif isinstance(s, ast.If):
                walk(s.body, in_with, loop_stack)
                walk(s.orelse, in_with, loop_stack)

    for body in (node.body, getattr(node, "orelse", []) or []):
        walk(body, False, [])
    out.sort(key=lambda cl: cl[1])
    return out
