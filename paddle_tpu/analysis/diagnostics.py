"""Structured diagnostics shared by the trace-safety linter, the graph
doctor, and the dy2static converter's runtime errors (ref: the ErrorData /
error-report machinery in python/paddle/jit/dy2static/error.py (U) — there a
runtime failure inside translated code is re-raised with the ORIGINAL
dygraph source location and a suggestion; here the same structured record
{code, severity, file, line, message, hint} backs three surfaces: the
pre-trace linter, the post-build graph doctor, and the converter's
"deliberately NOT converted" runtime error, so the CLI and the runtime tell
one story).

Rule codes are stable identifiers (PTA = Paddle-Tpu Analysis):

- PTA0xx  constructs the dy2static converter deliberately does not stage
          (the machine-checked form of the `jit/dy2static.py` docstring
          contract)
- PTA1xx  concretization hazards (host-value reads of possibly-traced data)
- PTA2xx  retrace hazards (per-step recompilation / stale captures)
- PTA3xx  side effects under trace (mutations the staged program drops)
- PTA4xx  repo-facing self-lint rules for library code
- PTA5xx  graph-doctor findings on a recorded Program / traced jaxpr
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

__all__ = ["Diagnostic", "Rule", "RULES", "TraceSafetyWarning",
           "ERROR", "WARNING", "INFO", "scan_statement"]

ERROR = "error"
WARNING = "warning"
INFO = "info"


class TraceSafetyWarning(UserWarning):
    """Emitted by `to_static(..., check=True)` at decoration time."""


@dataclass(frozen=True)
class Rule:
    code: str
    severity: str
    title: str
    hint: str
    # which upstream dy2static/program-validation error this rule mirrors
    # (surfaced in docs/PARITY.md)
    mirrors: str = ""


@dataclass
class Diagnostic:
    code: str
    severity: str
    file: str
    line: int
    message: str
    hint: str = ""

    def format(self, with_hint=True):
        s = f"{self.file}:{self.line}: {self.code} {self.severity}: " \
            f"{self.message}"
        if with_hint and self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def __str__(self):
        return self.format()


_RULE_LIST = [
    # ---- PTA0xx: the converter's "deliberately NOT converted" contract
    Rule("PTA001", WARNING,
         "`del` inside a convertible control-flow body",
         "the if/while stays plain Python: fine for concrete predicates, "
         "but a traced tensor predicate will fail at run time — hoist the "
         "`del` out of the branch/loop body",
         mirrors="dy2static ifelse_transformer unsupported-stmt fallback"),
    Rule("PTA002", WARNING,
         "`global`/`nonlocal` declaration inside a convertible "
         "control-flow body",
         "staged branches carry assigned names as explicit dataflow; "
         "declare the name outside the if/while and assign through a local",
         mirrors="dy2static create_nonlocal_stmts limitation"),
    Rule("PTA003", WARNING,
         "`while/else` / `for/else` is never staged",
         "the else clause has no lax equivalent — restructure as a flag "
         "checked after the loop",
         mirrors="dy2static loop_transformer (no else-clause support)"),
    Rule("PTA004", WARNING,
         "early exit (`return`/`break`/`continue`) inside `with`/`try`",
         "the early-exit rewrite cannot guard statements across a context "
         "manager or exception handler — move the exit out of the "
         "with/try block",
         mirrors="dy2static return_transformer unsupported placement"),
    Rule("PTA005", ERROR,
         "generator/coroutine passed to to_static",
         "yield/await cannot be staged into one XLA program; make the "
         "function return whole tensors (e.g. a stacked scan output)",
         mirrors="dy2static convert_call generator passthrough"),
    Rule("PTA006", WARNING,
         "`return` inside a non-range `for` loop",
         "only `for i in range(...)` (and `for x in <tensor>`) loops get "
         "the early-exit rewrite — iterate by index or restructure",
         mirrors="dy2static break_continue_transformer scope limits"),
    Rule("PTA007", WARNING,
         "early exit the staging rewrite cannot reach",
         "this return/break/continue survives the early-exit rewrite, so "
         "the enclosing statement stays plain Python and fails for traced "
         "predicates — simplify the exit structure",
         mirrors="dy2static return_transformer fallback"),
    # ---- PTA1xx: concretization hazards
    Rule("PTA101", WARNING,
         "concretization: host read of a possibly-traced value",
         ".numpy()/.item()/.tolist() force a device sync and raise under "
         "jit tracing — keep the computation in tensor ops, or move the "
         "host read outside the traced function",
         mirrors="Variable.numpy() restriction under @to_static"),
    Rule("PTA102", WARNING,
         "concretization: int()/float()/bool() on a possibly-traced value",
         "Python scalar coercion needs a concrete value and raises a "
         "TracerError under jit — use tensor ops (astype/cast, comparisons) "
         "instead",
         mirrors="dy2static convert_var_dtype"),
    Rule("PTA103", ERROR,
         "tensor-dependent branch in a scope the converter cannot stage",
         "this if/while predicate depends on traced data but the statement "
         "contains an unconvertible construct, so it will raise at trace "
         "time — fix the construct or keep the predicate concrete",
         mirrors="dy2static ifelse_transformer + error.py report"),
    # ---- PTA2xx: retrace hazards
    Rule("PTA201", WARNING,
         "mutable global read under trace",
         "the value is captured as a compile-time constant: later mutations "
         "are silently ignored by cached traces — pass it as an argument "
         "or make it an immutable constant",
         mirrors="ProgramCache keyed on function + input signature"),
    Rule("PTA202", WARNING,
         "Python-side RNG under trace",
         "random()/np.random draw ONCE at trace time and bake the value "
         "into the compiled program — use paddle.rand/randn (traced, keyed "
         "RNG) instead",
         mirrors="dygraph-vs-static RNG divergence (seed program ops)"),
    Rule("PTA203", INFO,
         "shape-dependent Python branching",
         "branching on .shape specializes the trace: every new input shape "
         "recompiles — pad to fixed shapes or mark the dim dynamic in "
         "InputSpec",
         mirrors="to_static input_spec re-trace policy"),
    # ---- PTA3xx: side effects under trace
    Rule("PTA301", WARNING,
         "mutation of module/self state under trace",
         "attribute writes on the layer run at TRACE time, not per step; "
         "buffers must flow through return values (or register_buffer) to "
         "update inside the compiled program",
         mirrors="dy2static convert_attr / parameter write-back rules"),
    Rule("PTA302", WARNING,
         "mutation of an outer container under trace",
         "append/update on a closure or global container runs once at "
         "trace time (and leaks tracers out of the trace) — accumulate in "
         "a local and return it",
         mirrors="dy2static list_transformer (tensor-array conversion)"),
    # ---- PTA4xx: repo-facing self-lint
    Rule("PTA401", ERROR,
         "module-level jax.jit without static-arg annotation",
         "a jit created at import time hashes every non-array argument by "
         "value on each call; annotate static_argnums/static_argnames (or "
         "build the jit inside the function where config rides the "
         "closure)",
         mirrors="to_static input_spec contract"),
    Rule("PTA402", ERROR,
         "possibly tracer-leaking store into a module-level cache",
         "storing an argument-derived value into module state from inside "
         "potentially-traced code can leak tracers across traces; key "
         "caches on concrete metadata only, or suppress with `# noqa: "
         "PTA402` after verifying only concrete values reach this line",
         mirrors="ProgramCache lifetime rules"),
    # ---- PTA5xx: graph doctor
    Rule("PTA501", WARNING,
         "dead node: recorded op unreachable from any fetch",
         "the op was recorded into the Program (or traced into the jaxpr) "
         "but no fetch depends on it — dead compute is compiled and "
         "executed for effects-free ops by the reference executor; remove "
         "it or fetch its output",
         mirrors="Program prune/garbage-collection pass"),
    Rule("PTA502", WARNING,
         "unused feed: placeholder/input never consumed",
         "the feed is declared but no fetched value depends on it — drop "
         "the placeholder or wire it into the graph",
         mirrors="Executor feed/fetch validation"),
    Rule("PTA503", WARNING,
         "silent dtype widening",
         "a low-precision operand (bf16/f16) is silently promoted to f32+ "
         "(or f32 to f64 under x64): the op runs at the wide dtype and the "
         "memory/speed benefit of the narrow dtype is lost — cast "
         "explicitly or align operand dtypes",
         mirrors="AMP o2 white/black-list promotion checks"),
    Rule("PTA504", WARNING,
         "host-callback/sync point inside the compiled program",
         "a host callback serializes the device pipeline every step — "
         "replace debug callbacks/py callbacks with traced ops, or hoist "
         "them out of the hot program",
         mirrors="InterpreterCore D2H sync detection"),
    Rule("PTA505", ERROR,
         "collective over a mesh axis that is not bound",
         "the program psums/gathers over an axis name absent from the "
         "device mesh — it will fail (or silently no-op) at dispatch; "
         "check fleet topology axis names ('dp','pp','sharding','sep',"
         "'mp')",
         mirrors="ProcessGroup ring-id validation on c_* ops"),
]

RULES = {r.code: r for r in _RULE_LIST}


def make(code, file, line, message=None, severity=None, hint=None):
    """Build a Diagnostic from the registry, with optional overrides."""
    r = RULES[code]
    return Diagnostic(code=code, severity=severity or r.severity,
                      file=file, line=int(line),
                      message=message or r.title, hint=hint or r.hint)


# --------------------------------------------------------------------------
# The "deliberately NOT converted" contract of jit/dy2static.py as a
# machine-checked classifier. `scan_statement` reports, for ONE if/while/for
# statement, every reason the converter will leave it as plain Python —
# used by the linter (PTA0xx findings) and by the converter itself to cite
# the matching code in its runtime error.

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _is_range_call(it):
    return (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range" and not it.keywords
            and 1 <= len(it.args) <= 3
            and not any(isinstance(a, ast.Starred) for a in it.args))


def scan_statement(node, include_plain_exits=False):
    """Reasons `node` (an ast.If / ast.While / ast.For) cannot be staged,
    as [(code, lineno)] in source order. With include_plain_exits, a bare
    return/break/continue remaining in the body (i.e. one the early-exit
    rewrite did not consume) reports as PTA007 — the converter uses that
    form; the linter does not (plain exits normally DO stage)."""
    out = []
    if isinstance(node, (ast.While, ast.For, ast.AsyncFor)) and node.orelse:
        out.append(("PTA003", node.lineno))

    def walk(stmts, in_with, loop_stack):
        for s in stmts:
            if isinstance(s, _SCOPES):
                continue
            if isinstance(s, ast.Delete):
                out.append(("PTA001", s.lineno))
            elif isinstance(s, (ast.Global, ast.Nonlocal)):
                out.append(("PTA002", s.lineno))
            elif isinstance(s, (ast.Return, ast.Break, ast.Continue)):
                if in_with:
                    out.append(("PTA004", s.lineno))
                elif isinstance(s, ast.Return) and "iter" in loop_stack:
                    out.append(("PTA006", s.lineno))
                elif isinstance(s, (ast.Break, ast.Continue)) \
                        and loop_stack:
                    pass        # belongs to the inner loop's own rewrite
                elif include_plain_exits:
                    out.append(("PTA007", s.lineno))
            if isinstance(s, (ast.With, ast.AsyncWith)):
                walk(s.body, True, loop_stack)
            elif isinstance(s, ast.Try):
                for blk in (s.body, s.orelse, s.finalbody):
                    walk(blk, True, loop_stack)
                for h in s.handlers:
                    walk(h.body, True, loop_stack)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                if s.orelse:
                    out.append(("PTA003", s.lineno))
                kind = ("range" if isinstance(s, ast.For)
                        and _is_range_call(s.iter) else "iter")
                walk(s.body, in_with, loop_stack + [kind])
                walk(s.orelse, in_with, loop_stack)
            elif isinstance(s, ast.While):
                if s.orelse:
                    out.append(("PTA003", s.lineno))
                walk(s.body, in_with, loop_stack + ["while"])
                walk(s.orelse, in_with, loop_stack)
            elif isinstance(s, ast.If):
                walk(s.body, in_with, loop_stack)
                walk(s.orelse, in_with, loop_stack)

    for body in (node.body, getattr(node, "orelse", []) or []):
        walk(body, False, [])
    out.sort(key=lambda cl: cl[1])
    return out
