"""`python -m paddle_tpu.analysis <file-or-package> [...]` — lint python
sources for trace-safety and library self-lint findings.

Exit status: 0 when no error-severity diagnostics, 1 otherwise (warnings
and infos print but do not fail the run), 2 on usage errors. `--strict`
fails on warnings too; `--mode trace` treats EVERY function as traced
(the default `package` mode applies trace rules only under `to_static`
decorators and self-lint rules everywhere).
"""

from __future__ import annotations

import argparse
import os
import sys

from .diagnostics import ERROR, WARNING
from .trace_lint import lint_file

__all__ = ["main"]


def _iter_py_files(path):
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="trace-safety linter for to_static programs")
    ap.add_argument("paths", nargs="+",
                    help="python files or package directories")
    ap.add_argument("--mode", choices=("package", "trace"),
                    default="package",
                    help="package: trace rules only under @to_static; "
                         "trace: every function is assumed traced")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings as well as errors")
    ap.add_argument("--no-hint", action="store_true",
                    help="omit hint lines from the report")
    args = ap.parse_args(argv)

    n_err = n_warn = n_files = 0
    for path in args.paths:
        if not os.path.exists(path):
            print(f"paddle_tpu.analysis: no such path: {path}",
                  file=sys.stderr)
            return 2
        for f in _iter_py_files(path):
            n_files += 1
            for d in lint_file(f, mode=args.mode):
                print(d.format(with_hint=not args.no_hint))
                if d.severity == ERROR:
                    n_err += 1
                elif d.severity == WARNING:
                    n_warn += 1
    print(f"paddle_tpu.analysis: {n_files} file(s), {n_err} error(s), "
          f"{n_warn} warning(s)")
    if n_err or (args.strict and n_warn):
        return 1
    return 0
