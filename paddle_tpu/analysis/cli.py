"""`python -m paddle_tpu.analysis <file-or-package> [...]` — lint python
sources for trace-safety and library self-lint findings.

Exit-code contract (stable — CI depends on it, don't grep rendered
text):

- **0** — clean: no error-severity diagnostics (no warnings either
  under ``--strict``).
- **1** — findings: at least one unsuppressed error (or warning with
  ``--strict``).
- **2** — internal/usage error: bad arguments, missing paths, or an
  analyzer crash.  Never means "findings".

``--serving`` adds the phase-2 serving-stack analyzers (thread-
ownership/lock-discipline lint PTA51x and the AST half of the donation
doctor PTA60x) on top of the trace lint.  ``--json`` replaces the
rendered report with one JSON object on stdout::

    {"files": N, "errors": N, "warnings": N,
     "diagnostics": [{"code", "severity", "file", "line",
                      "message", "hint"}, ...]}

`--mode trace` treats EVERY function as traced (the default `package`
mode applies trace rules only under `to_static` decorators and
self-lint rules everywhere).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .diagnostics import ERROR, WARNING
from .trace_lint import lint_file

__all__ = ["main"]


def _iter_py_files(path):
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def _run(args):
    if args.serving:
        from . import donation_doctor, serving_lint

    # Dedupe across overlapping path args (e.g. `paddle_tpu/serving/
    # paddle_tpu/serving/gateway/`) so no file is linted — or counted —
    # twice.
    seen = set()
    files = []
    for path in args.paths:
        if not os.path.exists(path):
            print(f"paddle_tpu.analysis: no such path: {path}",
                  file=sys.stderr)
            return 2
        for f in _iter_py_files(path):
            key = os.path.realpath(f)
            if key not in seen:
                seen.add(key)
                files.append(f)

    n_err = n_warn = 0
    collected = []
    for f in files:
        diags = list(lint_file(f, mode=args.mode))
        if args.serving:
            diags.extend(serving_lint.lint_file(f))
            diags.extend(donation_doctor.lint_file(f))
            diags.sort(key=lambda d: (d.file, d.line, d.code))
        for d in diags:
            if args.json:
                collected.append({
                    "code": d.code, "severity": d.severity,
                    "file": d.file, "line": d.line,
                    "message": d.message, "hint": d.hint,
                })
            else:
                print(d.format(with_hint=not args.no_hint))
            if d.severity == ERROR:
                n_err += 1
            elif d.severity == WARNING:
                n_warn += 1
    if args.json:
        json.dump({"files": len(files), "errors": n_err,
                   "warnings": n_warn, "diagnostics": collected},
                  sys.stdout, indent=2)
        print()
    else:
        print(f"paddle_tpu.analysis: {len(files)} file(s), "
              f"{n_err} error(s), {n_warn} warning(s)")
    if n_err or (args.strict and n_warn):
        return 1
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="trace-safety linter for to_static programs "
                    "(exit 0 clean / 1 findings / 2 internal error)")
    ap.add_argument("paths", nargs="+",
                    help="python files or package directories")
    ap.add_argument("--mode", choices=("package", "trace"),
                    default="package",
                    help="package: trace rules only under @to_static; "
                         "trace: every function is assumed traced")
    ap.add_argument("--serving", action="store_true",
                    help="also run the serving-stack analyzers "
                         "(thread-ownership lint PTA51x, donation "
                         "doctor PTA60x)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON report object instead of "
                         "rendered text")
    ap.add_argument("--strict", action="store_true",
                    help="exit nonzero on warnings as well as errors")
    ap.add_argument("--no-hint", action="store_true",
                    help="omit hint lines from the report")
    args = ap.parse_args(argv)

    try:
        return _run(args)
    except Exception as exc:  # exit 2: internal error, never "findings"
        print(f"paddle_tpu.analysis: internal error: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return 2
