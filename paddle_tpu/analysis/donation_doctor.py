"""Donation doctor (analysis phase 2): the engine's state-rebind
discipline as machine-checked rules.

The serving engine donates its hot buffers (`decode_donate`/
`prefill_donate` in ``Engine.__init__``) so XLA reuses them in place;
the price is a strict host-side discipline — every donated reference
must be REBOUND from the dispatch's outputs before anyone reads it
again.  PR 14's documented segfault class is exactly this discipline
broken (closing an engine whose live state aliased donated buffers).
Two surfaces:

**AST pass** (:func:`lint_source` / :func:`lint_file`, the
``--serving`` CLI path).  It binds ``X = CompiledFn(fn,
donate_argnums=...)`` / ``jax.jit(..., donate_argnums=...)`` specs —
resolving literal tuples, simple local names (including ``+=``
extensions, the engine's kv-quant pattern), and ``a if cond else b``
either-branch unions — then walks each call site of a bound spec:

- PTA601 use-after-donate: a donated name/attribute path is READ in a
  later statement of the same function before being re-assigned.
- PTA602 double donation: duplicate argnums in the spec, or one
  expression passed in two donated positions.
- PTA603 donated state escape: a donated ``self.*`` path that is
  neither re-assigned nor re-established through a method call on its
  owner (``self.pool.rebind(...)``) before the function ends — live
  engine state left aliasing a donated buffer.

**Jaxpr pass** (:func:`diagnose_donation`).  Traces the function
abstractly (``jax.make_jaxpr`` — no FLOPs run) and checks the donation
spec against the program itself: PTA602 duplicate/out-of-range
argnums, PTA604 donated inputs whose shape/dtype matches no output
(XLA cannot alias them — the donation only invalidates the host
reference).

False negatives are fine (it is a linter); false positives carry
``# noqa: PTA60x`` with a one-line justification.
"""

from __future__ import annotations

import ast
import textwrap

from .diagnostics import Diagnostic, apply_noqa_files, make
from .trace_lint import _dotted, apply_noqa

__all__ = ["lint_source", "lint_file", "diagnose_donation"]


# --------------------------------------------------------------------------
# donation-spec resolution


def _literal_ints(node):
    """frozenset of ints for a Tuple/List/Constant-int literal, else
    None (unresolvable)."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


def _resolve_argnums(node, local_literals):
    """Resolve a ``donate_argnums=`` value to a tuple of ints (possibly
    with duplicates, for PTA602), or None when it cannot be resolved
    statically.  ``local_literals`` maps local names to accumulated
    literal tuples (Assign + AugAssign extension)."""
    lit = _literal_ints(node)
    if lit is not None:
        return lit
    if isinstance(node, ast.Name):
        return local_literals.get(node.id)
    if isinstance(node, ast.IfExp):
        # `donate_argnums=decode_donate if donate else ()` — the engine
        # pattern; analyze the union of resolvable branches so the
        # donating configuration is what gets checked
        a = _resolve_argnums(node.body, local_literals)
        b = _resolve_argnums(node.orelse, local_literals)
        if a is None and b is None:
            return None
        return tuple(a or ()) + tuple(b or ())
    return None


def _collect_local_literals(fdef):
    """name -> accumulated literal int tuple for simple assignments in
    one function body (``x = (1, 2)`` then ``x += (3,)`` accumulates —
    branches are unioned, matching the kv-quant donate pattern)."""
    out = {}
    for node in ast.walk(fdef):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            lit = _literal_ints(node.value)
            if lit is not None:
                out[node.targets[0].id] = \
                    out.get(node.targets[0].id, ()) + lit
        elif isinstance(node, ast.AugAssign) \
                and isinstance(node.target, ast.Name) \
                and isinstance(node.op, ast.Add):
            lit = _literal_ints(node.value)
            if lit is not None and node.target.id in out:
                out[node.target.id] = out[node.target.id] + lit
    return out


def _is_compiled_ctor(call):
    d = _dotted(call.func) or ""
    last = d.split(".")[-1]
    return last in ("CompiledFn", "jit")


def _donation_kw(call):
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return kw.value
    return None


def _spec_target(node):
    """Dotted key for the assignment target holding a compiled fn:
    a Name or a self-attribute chain."""
    d = _dotted(node)
    return d


# --------------------------------------------------------------------------
# per-function call-site analysis


def _stmt_stores(stmt):
    """Dotted paths a statement assigns to (direct re-binds)."""
    out = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    flat = []
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            flat.extend(t.elts)
        else:
            flat.append(t)
    for t in flat:
        d = _dotted(t)
        if d is not None:
            out.add(d)
    return out


def _loads_in(node, paths):
    """(path, lineno) for every Load of a dotted path in ``paths``
    inside ``node`` — exact-path matches only."""
    hits = []
    for n in ast.walk(node):
        if isinstance(n, (ast.Attribute, ast.Name)) \
                and isinstance(getattr(n, "ctx", None), ast.Load):
            d = _dotted(n)
            if d in paths:
                hits.append((d, n.lineno))
    return hits


def _own_calls(stmt):
    """Calls in a statement's OWN expressions only — compound bodies
    belong to the nested statements, which the linear scan visits
    separately."""
    if isinstance(stmt, (ast.If, ast.While)):
        exprs = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        exprs = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        exprs = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, ast.Try):
        exprs = []
    else:
        return [n for n in ast.walk(stmt) if isinstance(n, ast.Call)]
    out = []
    for e in exprs:
        out.extend(n for n in ast.walk(e) if isinstance(n, ast.Call))
    return out


def _owner_method_calls(stmt):
    """Dotted receivers of method calls in a statement — a call on
    ``self.pool`` re-establishes ``self.pool.*`` donated paths (the
    ``pool.rebind(new_k, ...)`` idiom)."""
    out = set()
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            d = _dotted(n.func.value)
            if d is not None:
                out.add(d)
    return out


class _DonationLinter:
    def __init__(self, filename):
        self.filename = filename
        self.diags = []
        #: spec key (dotted) -> tuple of donated argnums
        self.specs = {}

    def emit(self, code, line, message=None):
        self.diags.append(make(code, self.filename, line,
                               message=message))

    # -- pass 1: bind donation specs --------------------------------------
    def collect_specs(self, tree):
        visited = set()
        for fdef in [n for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))] + [tree]:
            local_literals = _collect_local_literals(fdef) \
                if not isinstance(fdef, ast.Module) else {}
            for node in ast.walk(fdef):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.value, ast.Call)
                        and _is_compiled_ctor(node.value)):
                    continue
                if id(node) in visited:
                    continue          # nested defs are walked twice
                visited.add(id(node))
                kw = _donation_kw(node.value)
                if kw is None:
                    continue
                argnums = _resolve_argnums(kw, local_literals)
                key = _spec_target(node.targets[0])
                if argnums is None or key is None:
                    continue
                dupes = sorted({a for a in argnums
                                if argnums.count(a) > 1})
                if dupes:
                    self.emit(
                        "PTA602", node.lineno,
                        message=f"donate_argnums for {key!r} donates "
                                f"position(s) {dupes} more than once")
                self.specs[key] = tuple(sorted(set(argnums)))

    # -- pass 2: call sites ------------------------------------------------
    def check_function(self, fdef):
        stmts = self._linear_stmts(fdef)
        for i, stmt in enumerate(stmts):
            for call in _own_calls(stmt):
                key = _dotted(call.func)
                if key is None or key not in self.specs:
                    continue
                self._check_site(call, stmt, stmts[i + 1:])

    def _linear_stmts(self, fdef):
        """Function statements flattened in source order (branch bodies
        inline) — the linear scan use-after-donate rides on."""
        out = []

        def walk(body):
            for s in body:
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    continue
                out.append(s)
                for attr in ("body", "orelse", "finalbody"):
                    walk(getattr(s, attr, None) or [])
                for h in getattr(s, "handlers", ()) or ():
                    walk(h.body)

        walk(fdef.body)
        out.sort(key=lambda s: s.lineno)
        return out

    def _check_site(self, call, call_stmt, later_stmts):
        argnums = self.specs[_dotted(call.func)]
        donated = {}                  # dotted path -> argnum
        seen_exprs = {}
        for pos in argnums:
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            d = _dotted(arg)
            if d is None:
                continue
            if d in seen_exprs:
                self.emit(
                    "PTA602", call.lineno,
                    message=f"{d!r} is passed in two donated positions "
                            f"({seen_exprs[d]} and {pos}) — one buffer "
                            "cannot alias two outputs")
            else:
                seen_exprs[d] = pos
                donated[d] = pos
        if not donated:
            return
        # what the CALL STATEMENT itself rebinds (outputs assigned back)
        poisoned = set(donated) - _stmt_stores(call_stmt)
        unrebound_self = {d for d in poisoned if d.startswith("self.")}
        for stmt in later_stmts:
            if not poisoned:
                break
            reads = _loads_in(stmt, poisoned)
            stores = _stmt_stores(stmt) & poisoned
            owner_calls = _owner_method_calls(stmt)
            # a read in the same statement that re-binds the path is the
            # rebind itself (`x = f(x)` later) — stores win on ties
            for d, line in reads:
                if d in stores:
                    continue
                self.emit(
                    "PTA601", line,
                    message=f"{d!r} was donated to the dispatch at line "
                            f"{call.lineno} and read here before being "
                            "rebound")
                poisoned.discard(d)
                unrebound_self.discard(d)
            poisoned -= stores
            unrebound_self -= stores
            # `self.pool.rebind(...)` re-establishes self.pool.* paths
            rebound = {d for d in poisoned
                       if any(d.startswith(owner + ".")
                              for owner in owner_calls)}
            poisoned -= rebound
            unrebound_self -= rebound
        for d in sorted(unrebound_self):
            self.emit(
                "PTA603", call.lineno,
                message=f"donated engine state {d!r} is never rebound "
                        "from the dispatch outputs — live state aliases "
                        "a donated buffer (the documented segfault "
                        "class)")


def lint_source(source, filename="<string>", line_offset=0):
    """Donation-discipline lint of python source; returns [Diagnostic]
    sorted by line, with `# noqa` applied."""
    source = textwrap.dedent(source)
    tree = ast.parse(source)
    linter = _DonationLinter(filename)
    linter.collect_specs(tree)
    if linter.specs:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                linter.check_function(node)
    diags = apply_noqa(linter.diags, source)
    for d in diags:
        d.line += line_offset
    diags.sort(key=lambda d: (d.line, d.code))
    return diags


def lint_file(path):
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        return lint_source(src, filename=str(path))
    except SyntaxError as e:
        return [Diagnostic(code="PTA000", severity="error",
                           file=str(path), line=int(e.lineno or 0),
                           message=f"could not parse: {e.msg}", hint="")]


# --------------------------------------------------------------------------
# jaxpr surface


def diagnose_donation(fn, *args, donate_argnums=(), file=None, **kwargs):
    """Trace ``fn(*args)`` abstractly and check ``donate_argnums``
    against the program: PTA602 duplicate/out-of-range argnums, PTA604
    donated inputs with no shape/dtype-matching output (XLA cannot
    alias them).  ``fn`` may also be a serving ``CompiledFn`` — its
    wrapped function and recorded donate spec are used.  Returns
    [Diagnostic]."""
    import jax

    inner = getattr(fn, "_jit", None) or getattr(fn, "_fn", None) or fn
    spec = tuple(donate_argnums) or tuple(getattr(fn, "_donate", ()))
    code = getattr(inner, "__code__", None)
    f = file or (code.co_filename if code is not None else "<jaxpr>")
    line = code.co_firstlineno if code is not None else 0
    diags = []
    seen = set()
    for a in spec:
        if a in seen:
            diags.append(make(
                "PTA602", f, line,
                message=f"donate_argnums donates position {a} twice"))
        seen.add(a)
    closed = jax.make_jaxpr(inner)(*args, **kwargs)
    invars = closed.jaxpr.invars
    out_shapes = {(tuple(v.aval.shape), str(v.aval.dtype))
                  for v in closed.jaxpr.outvars
                  if hasattr(v, "aval")}
    for a in sorted(seen):
        if not 0 <= a < len(invars):
            diags.append(make(
                "PTA602", f, line,
                message=f"donate_argnums position {a} is out of range "
                        f"for a {len(invars)}-input program"))
            continue
        aval = invars[a].aval
        key = (tuple(aval.shape), str(aval.dtype))
        if key not in out_shapes:
            diags.append(make(
                "PTA604", f, line,
                message=f"donated input #{a} ({key[1]}{list(key[0])}) "
                        "matches no output shape/dtype — the donation "
                        "is wasted"))
    diags.sort(key=lambda d: (d.line, d.code))
    return apply_noqa_files(diags)
