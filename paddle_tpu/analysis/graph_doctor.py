"""Graph doctor: post-build analysis of a recorded `static.graph` Program
or a traced jaxpr (ref: the Program validation/prune passes around the
reference Executor — prune_backward, feed/fetch checking, and the
InterpreterCore's D2H-sync detection; here the same questions are asked of
the recorded _Node DAG and of the jaxpr that IS the program).

Findings (see diagnostics.RULES):

- PTA501  dead node — recorded/traced but unreachable from any fetch
- PTA502  unused feed — placeholder/input no fetch depends on
- PTA503  silent dtype widening (bf16/f16 operand promoted to f32+,
          f32 promoted to f64)
- PTA504  host-callback/sync point compiled into the program
- PTA505  collective over an axis name that is not bound in the mesh

Entry points:

- ``diagnose_program(fetch_list, program=None)`` — inspect a static-mode
  Program (uses ``Program.nodes``, the creation-order op record).
- ``diagnose_jaxpr(closed_jaxpr, mesh_axes=None)`` — inspect any jaxpr.
- ``doctor(fn, *example_args, mesh_axes=None)`` — trace ``fn`` abstractly
  (no FLOPs run) and diagnose the resulting jaxpr.
"""

from __future__ import annotations

import numpy as np

from .diagnostics import make

__all__ = ["diagnose_program", "diagnose_jaxpr", "doctor"]

_NARROW = ("bfloat16", "float16")
_WIDE = ("float32", "float64")
_CALLBACK_PRIMS = ("pure_callback", "debug_callback", "io_callback",
                   "host_callback", "outside_call", "debug_print")


def _widening(in_dtype, out_dtype):
    i, o = str(in_dtype), str(out_dtype)
    if i in _NARROW and o in _WIDE:
        return True
    return i == "float32" and o == "float64"


# --------------------------------------------------------------------------
# Program doctor


def _aval_of(x):
    """(dtype, weak_type) of a recorded node input, or None for
    non-arrays."""
    from ..static.graph import _SymArr, _ParamRef

    if isinstance(x, _SymArr):
        return x.aval.dtype, bool(getattr(x.aval, "weak_type", False))
    if isinstance(x, _ParamRef):
        d = getattr(x.t._data, "dtype", None)
        return (d, bool(getattr(x.t._data, "weak_type", False))) \
            if d is not None else None
    d = getattr(x, "dtype", None)
    if d is not None and not isinstance(x, (bool, int, float)):
        return d, bool(getattr(x, "weak_type", False))
    return None


def diagnose_program(fetch_list, program=None, file="<static.Program>"):
    """Diagnose a recorded static Program against the given fetches.
    ``fetch_list`` holds symbolic Tensors (as passed to Executor.run).
    Line numbers are 1-based positions in the program's creation-order
    node record."""
    from ..core.tensor import Tensor
    from ..static import graph as G

    prog = program if program is not None else G.default_main_program()
    syms = []
    for f in fetch_list:
        s = f._data if isinstance(f, Tensor) else f
        if not isinstance(s, G._SymArr):
            raise TypeError("diagnose_program: fetch_list entries must be "
                            "static-program Tensors")
        syms.append(s)

    # reachability from the fetches
    live, used_feeds = set(), set()
    stack = [s.node for s in syms if s.node is not None]
    used_feeds |= {s.feed_name for s in syms if s.feed_name is not None}
    while stack:
        n = stack.pop()
        if id(n) in live:
            continue
        live.add(id(n))
        for x in n.inputs:
            if isinstance(x, G._SymArr):
                if x.feed_name is not None:
                    used_feeds.add(x.feed_name)
                elif x.node is not None:
                    stack.append(x.node)

    diags = []
    nodes = list(getattr(prog, "nodes", ()) or ())
    for pos, n in enumerate(nodes, start=1):
        if id(n) not in live:
            diags.append(make(
                "PTA501", file, pos,
                message=f"dead node: op {n.op_name!r} (recorded op #{pos}) "
                        "is unreachable from the fetch_list"))
            continue
        out_avals = getattr(n, "out_avals", None) or ()
        in_avals = [a for a in map(_aval_of, n.inputs) if a is not None]
        for out in out_avals:
            odt = getattr(out, "dtype", None)
            if odt is None:
                continue
            for idt, weak in in_avals:
                if not weak and _widening(idt, odt):
                    diags.append(make(
                        "PTA503", file, pos,
                        message=f"op {n.op_name!r} (recorded op #{pos}) "
                                f"silently widens {idt} operand to {odt}"))
                    break
            else:
                continue
            break
    for pos, (name, ph) in enumerate(sorted(prog.placeholders.items()),
                                     start=1):
        if name not in used_feeds:
            diags.append(make(
                "PTA502", file, 0,
                message=f"unused feed: placeholder {name!r} is never "
                        "consumed by the fetched subgraph"))
    diags.sort(key=lambda d: (d.line, d.code))
    return diags


# --------------------------------------------------------------------------
# jaxpr doctor


def _eqn_line(eqn, default=0):
    try:  # best effort: jax internal source-info API
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.start_line
    except Exception:
        pass
    return default


def _eqn_file(eqn, default="<jaxpr>"):
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name
    except Exception:
        pass
    return default


def _axis_names(params):
    """str axis names mentioned by a collective eqn's params."""
    names = []
    for key in ("axes", "axis_name", "axis_index_groups_axis"):
        v = params.get(key)
        if v is None:
            continue
        for a in (v if isinstance(v, (tuple, list)) else (v,)):
            if isinstance(a, str):
                names.append(a)
    return names


def _sub_jaxprs(params):
    import jax

    for v in params.values():
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif hasattr(v, "eqns") and hasattr(v, "outvars"):
            yield v
        elif isinstance(v, (tuple, list)):
            for w in v:
                if isinstance(w, jax.core.ClosedJaxpr):
                    yield w.jaxpr
                elif hasattr(w, "eqns") and hasattr(w, "outvars"):
                    yield w


def diagnose_jaxpr(closed_jaxpr, mesh_axes=None, file="<jaxpr>"):
    """Diagnose a (Closed)Jaxpr. ``mesh_axes``: the axis names the program
    will run under (e.g. fleet topology dims); collectives over other
    names report PTA505. With mesh_axes=None the axis check is skipped."""
    import jax

    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    mesh_axes = set(mesh_axes) if mesh_axes is not None else None
    diags = []

    # ---- liveness, walked backward; effectful eqns stay live ----
    live_vars = {v for v in jaxpr.outvars
                 if not isinstance(v, jax.core.Literal)}
    live_eqns = [False] * len(jaxpr.eqns)
    for i in range(len(jaxpr.eqns) - 1, -1, -1):
        eqn = jaxpr.eqns[i]
        effectful = bool(getattr(eqn, "effects", None)) \
            or eqn.primitive.name in _CALLBACK_PRIMS
        if effectful or any(v in live_vars for v in eqn.outvars):
            live_eqns[i] = True
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    live_vars.add(v)

    for i, eqn in enumerate(jaxpr.eqns):
        f = _eqn_file(eqn, file)
        ln = _eqn_line(eqn, i + 1)
        pname = eqn.primitive.name
        if not live_eqns[i]:
            diags.append(make(
                "PTA501", f, ln,
                message=f"dead compute: {pname!r} (eqn #{i + 1}) does not "
                        "feed any program output"))
            continue
        # host callbacks / sync points
        if pname in _CALLBACK_PRIMS or "callback" in pname:
            diags.append(make(
                "PTA504", f, ln,
                message=f"host callback {pname!r} compiled into the "
                        "program serializes the device pipeline"))
        # silent dtype widening at promotion sites
        if pname == "convert_element_type":
            src = eqn.invars[0]
            odt = eqn.params.get("new_dtype")
            sdt = getattr(src.aval, "dtype", None)
            weak = bool(getattr(src.aval, "weak_type", False))
            if sdt is not None and odt is not None and not weak \
                    and _widening(sdt, odt):
                diags.append(make(
                    "PTA503", f, ln,
                    message=f"implicit promotion widens {sdt} to {odt}"))
        # collectives over unbound axes
        if mesh_axes is not None:
            for name in _axis_names(eqn.params):
                if name not in mesh_axes:
                    diags.append(make(
                        "PTA505", f, ln,
                        message=f"collective {pname!r} runs over axis "
                                f"{name!r}, not bound in the mesh "
                                f"(axes: {sorted(mesh_axes)})"))
        sub_axes = mesh_axes
        if mesh_axes is not None and "shard_map" in pname:
            # shard_map binds its mesh's axis names for the body, even
            # when the shard_map itself sits under lax.scan (the
            # MeshEngine decode shape) — collectives over those axes
            # are well-bound, not PTA505.
            mesh = eqn.params.get("mesh")
            if mesh is not None:
                shape = getattr(mesh, "shape", None)
                if shape:
                    sub_axes = mesh_axes | set(dict(shape))
        for sub in _sub_jaxprs(eqn.params):
            diags.extend(diagnose_jaxpr(sub, mesh_axes=sub_axes, file=f))

    # ---- unused invars ----
    for j, v in enumerate(jaxpr.invars):
        if v not in live_vars:
            diags.append(make(
                "PTA502", file, 0,
                message=f"unused input: argument #{j + 1} never reaches "
                        "any program output"))
    diags.sort(key=lambda d: (d.file, d.line, d.code))
    return diags


def doctor(fn, *example_args, mesh_axes=None, axis_env=None, **kwargs):
    """Trace ``fn`` abstractly over example args (paddle Tensors, arrays,
    or ShapeDtypeStructs — no FLOPs run) and diagnose the jaxpr. Extra
    ``kwargs`` pass through to ``fn``. ``axis_env``: [(name, size)] pairs
    binding collective axes for tracing (defaults to mesh_axes with a
    dummy size of 1... sizes only matter for axis_index)."""
    import jax

    from ..core.tensor import Tensor

    def to_spec(a):
        if isinstance(a, Tensor):
            d = a._data
            return jax.ShapeDtypeStruct(tuple(d.shape), d.dtype)
        if isinstance(a, jax.ShapeDtypeStruct):
            return a
        arr = np.asarray(a)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    specs = [to_spec(a) for a in example_args]
    target = getattr(fn, "forward", None) if not callable(fn) else fn
    inner = fn if callable(fn) else target

    def wrapped(*arrays):
        args = [Tensor(a) for a in arrays]
        out = inner(*args, **kwargs)
        leaves = out if isinstance(out, (tuple, list)) else [out]
        return tuple(o._data if isinstance(o, Tensor) else o
                     for o in leaves)

    if axis_env is None and mesh_axes:
        axis_env = [(name, 2) for name in mesh_axes]
    closed = jax.make_jaxpr(wrapped, axis_env=axis_env or None)(*specs)
    srcfile = getattr(getattr(inner, "__code__", None), "co_filename",
                      "<jaxpr>")
    return diagnose_jaxpr(closed, mesh_axes=mesh_axes, file=srcfile)
