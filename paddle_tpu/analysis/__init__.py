"""paddle_tpu.analysis — trace-safety linter + graph doctor for to_static
programs (ref: the dy2static error/validation layer, SURVEY.md §2.1–2.2).

Three passes share one structured-diagnostic engine:

- ``check(fn)`` / ``lint_source`` / ``lint_file``: AST trace-safety
  linting WITHOUT running the function (unconvertible constructs,
  concretization hazards, retrace hazards, side effects under trace).
- ``doctor(fn, *example_args)`` / ``diagnose_program`` /
  ``diagnose_jaxpr``: post-build graph analysis (dead nodes, unused
  feeds, dtype widening, host syncs, unbound collective axes).
- ``python -m paddle_tpu.analysis <path>``: the package self-lint CLI.

Every finding is a ``Diagnostic{code, severity, file, line, message,
hint}`` with a stable PTA rule code (see ``RULES`` and docs/PARITY.md);
``# noqa: PTA0xx`` on the flagged line suppresses it.
"""

from .diagnostics import (Diagnostic, Rule, RULES, TraceSafetyWarning,
                          ERROR, WARNING, INFO)
from .trace_lint import check, lint_source, lint_file
from .graph_doctor import doctor, diagnose_program, diagnose_jaxpr
from .cli import main

__all__ = [
    "Diagnostic", "Rule", "RULES", "TraceSafetyWarning",
    "ERROR", "WARNING", "INFO",
    "check", "lint_source", "lint_file",
    "doctor", "diagnose_program", "diagnose_jaxpr",
    "main",
]
