"""paddle_tpu.analysis — trace-safety linter + graph doctor for to_static
programs (ref: the dy2static error/validation layer, SURVEY.md §2.1–2.2).

Phase-1 passes share one structured-diagnostic engine:

- ``check(fn)`` / ``lint_source`` / ``lint_file``: AST trace-safety
  linting WITHOUT running the function (unconvertible constructs,
  concretization hazards, retrace hazards, side effects under trace).
- ``doctor(fn, *example_args)`` / ``diagnose_program`` /
  ``diagnose_jaxpr``: post-build graph analysis (dead nodes, unused
  feeds, dtype widening, host syncs, unbound collective axes).
- ``python -m paddle_tpu.analysis <path>``: the package self-lint CLI
  (exit contract: 0 clean / 1 findings / 2 internal error).

Phase 2 adds the serving-stack verifiers (``--serving`` on the CLI):

- ``serving_check(obj)`` / ``serving_lint``: thread-ownership and
  lock-discipline lint (PTA51x) — engine/pool/store mutation outside
  the owning worker thread, unlocked StreamHandle mutation, blocking
  under a lock, wall-clock in fault paths, undisciplined threads.
- ``diagnose_donation(fn, *args)`` / ``donation_doctor``: jaxpr-level
  donation doctor (PTA60x) — use-after-donate, double donation,
  donated buffers never rebound into engine state.
- ``check_balance`` / ``check_census`` / ``collective_balance``:
  collective-balance checker (PTA70x) — cond-branch census imbalance,
  collectives in unbounded loops, unbound axes, census drift vs the
  registered expected-census formulas.

Every finding is a ``Diagnostic{code, severity, file, line, message,
hint}`` with a stable PTA rule code (see ``RULES`` and docs/PARITY.md);
``# noqa: PTA0xx`` on the flagged line suppresses it.
"""

from .diagnostics import (Diagnostic, Rule, RULES, TraceSafetyWarning,
                          ERROR, WARNING, INFO, apply_noqa_files)
from .trace_lint import check, lint_source, lint_file
from .graph_doctor import doctor, diagnose_program, diagnose_jaxpr
from .serving_lint import serving_check
from .donation_doctor import diagnose_donation
from .collective_balance import (check_balance, check_census,
                                 register_expected_census)
from .cli import main

__all__ = [
    "Diagnostic", "Rule", "RULES", "TraceSafetyWarning",
    "ERROR", "WARNING", "INFO", "apply_noqa_files",
    "check", "lint_source", "lint_file",
    "doctor", "diagnose_program", "diagnose_jaxpr",
    "serving_check", "diagnose_donation",
    "check_balance", "check_census", "register_expected_census",
    "main",
]
