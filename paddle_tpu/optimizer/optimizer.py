"""Optimizer base (ref: python/paddle/optimizer/optimizer.py (U)).

Design: every optimizer's math lives in a pure `_update(param, grad, state,
lr) -> (new_param, new_state)` array function. Eager `.step()` applies it
mutating wrappers in-place (dygraph parity); the SAME function is reused by
jit.train_step and the distributed sharded optimizers, so there is exactly one
implementation of each update rule (the reference needs separate CPU/GPU/fused
kernels + multi_tensor paths — XLA fuses ours).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import tape as _tape
from ..nn.clip import ClipGradBase


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        from .lr import LRScheduler

        if parameters is None:
            from ..static.graph import in_static_mode

            if not in_static_mode():
                raise ValueError("parameters must be provided (dygraph mode)")
            # static mode (ref): parameters are discovered from the loss's
            # recorded DAG when minimize() is called
            self._parameter_list = None
        else:
            self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._regularizer_fn = None
        if weight_decay is None:
            self._weight_decay = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        else:  # paddle.regularizer object (L2Decay coeff path; L1Decay et al
            # contribute through their gradient-term callable)
            self._weight_decay = float(getattr(weight_decay, "_coeff", getattr(weight_decay, "coeff", 0.0)))
            from ..regularizer import L2Decay, WeightDecayRegularizer

            if (isinstance(weight_decay, WeightDecayRegularizer)
                    and not isinstance(weight_decay, L2Decay)):
                self._weight_decay = 0.0
                self._regularizer_fn = weight_decay
        # effective coupled-L2 coefficient for the param currently being
        # updated (set by _update_for; exemption zeroes it exactly instead
        # of cancelling the term in a lower precision)
        self._cur_wd = self._weight_decay
        self._accumulators = {}  # param id -> dict(state_name -> jnp array)
        self._step_count = 0
        self._param_names = {}
        for i, p in enumerate(self._parameter_list or []):
            self._param_names[id(p)] = p.name or f"param_{i}"

    # -------- lr --------
    def get_lr(self):
        from .lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate.get_lr()
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -------- state --------
    def _state_for(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._init_state(p)
            self._accumulators[id(p)] = st
        return st

    def _init_state(self, p):
        return {}

    def state_dict(self):
        out = {"LR_Scheduler": {}, "master_weights": {}}
        from .lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        for p in self._plist():
            name = self._param_names[id(p)]
            for k, v in self._accumulators.get(id(p), {}).items():
                out[f"{name}.{k}"] = Tensor(v) if not isinstance(v, Tensor) else v
        out["global_step"] = self._step_count
        return out

    def set_state_dict(self, state):
        from .lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler) and state.get("LR_Scheduler"):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        self._step_count = int(state.get("global_step", 0))
        for p in self._plist():
            name = self._param_names[id(p)]
            st = self._state_for(p)
            for k in list(st):
                key = f"{name}.{k}"
                if key in state:
                    v = state[key]
                    st[k] = v._data if isinstance(v, Tensor) else jnp.asarray(v)

    # -------- core --------
    def _update(self, param, grad, state, lr):
        raise NotImplementedError

    def _update_for(self, p, param, grad, state, lr):
        """Per-parameter update hook: like _update but with access to the
        Parameter object, so subclasses can apply per-param policy (AdamW's
        decoupled decay / lr_ratio — override _update_raw). Both eager
        step() and the compiled TrainStep route through this, and it PINS
        dtypes: a strong-typed f32 lr (the TrainStep path) must not promote
        bf16 params or optimizer state (state promotion would also change
        jit avals and force a full recompile every step)."""
        self._cur_wd = self._coupled_wd_for(p)
        new_p, new_state = self._update_raw(p, param, grad, state, lr)
        new_p = new_p.astype(param.dtype)
        new_state = jax.tree.map(
            lambda n, o: n.astype(o.dtype) if hasattr(o, "dtype") else n,
            new_state, state)
        return new_p, new_state

    def _update_raw(self, p, param, grad, state, lr):
        return self._update(param, grad, state, lr)

    def _decay_exempt(self, p):
        """AdamW-style decoupled decay skips biases/norms by convention flag."""
        return getattr(p, "no_weight_decay", False)

    def _coupled_wd_for(self, p):
        """Effective optimizer-level coupled-L2 coefficient for this param
        (reference precedence: a ParamAttr-attached regularizer REPLACES the
        optimizer-level one; a decay-exempt param gets none at all).
        Subclass _update math reads self._cur_wd so exemption is exact — no
        cancel-then-re-add round-trip through the grad dtype."""
        per_param = getattr(p, "regularizer", None)
        if (per_param is not None and callable(per_param)) \
                or self._decay_exempt(p):
            return 0.0
        return self._weight_decay

    def _regularized_grad(self, p, g_arr):
        """Add the winning gradient-term regularizer to `g_arr`. The coupled
        optimizer-level L2 is NOT handled here — _coupled_wd_for decides it
        and _update applies it (in f32 where the subclass math is f32)."""
        per_param = getattr(p, "regularizer", None)
        if per_param is not None and callable(per_param):
            # explicit user intent wins even on decay-exempt params
            return g_arr + per_param(p._data)
        if self._decay_exempt(p):
            return g_arr
        if self._regularizer_fn is not None:
            g_arr = g_arr + self._regularizer_fn(p._data)
        return g_arr

    def _plist(self):
        if self._parameter_list is None:
            raise RuntimeError(
                "optimizer has no parameters yet: it was built without a "
                "parameter list (static mode) — call minimize(loss) first")
        return self._parameter_list

    def step(self):
        params_grads = [(p, p.grad) for p in self._plist()
                        if p.trainable and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._step_count += 1
        lr = self.get_lr()
        with _tape.no_grad():
            for p, g in params_grads:
                if g is None:
                    continue
                state = self._state_for(p)
                param_lr = lr * p.optimize_attr.get("learning_rate", 1.0)
                g_arr = self._regularized_grad(p, g._data)
                new_p, new_state = self._update_for(p, p._data, g_arr, state,
                                                    param_lr)
                p._data = new_p
                self._accumulators[id(p)] = new_state

    def clear_grad(self, set_to_zero=False):
        for p in self._plist():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.graph import _is_sym, register_minimize

        if _is_sym(loss):
            # static mode (ref Optimizer.minimize over the Program):
            # register the train op; Executor.run applies the update
            return register_minimize(self, loss, parameters=parameters,
                                     no_grad_set=no_grad_set)
        loss.backward()
        self.step()
        return None, None

    # epoch-style lr step passthrough
    def _lr_step(self):
        from .lr import LRScheduler

        if isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.step()


def _apply_l2(grad, param, coeff):
    """Classic (coupled) L2 regularization: grad += coeff * param."""
    if coeff:
        return grad + coeff * param
    return grad
