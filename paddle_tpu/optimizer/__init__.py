from .optimizer import Optimizer
from .optimizers import (SGD, Momentum, Adagrad, Adadelta, RMSProp, Adam,
                         AdamW, Adamax, Lamb, Rprop, ASGD, NAdam, RAdam)
from .lbfgs import LBFGS
from . import lr
