from .optimizer import Optimizer
from .optimizers import SGD, Momentum, Adagrad, RMSProp, Adam, AdamW, Adamax, Lamb
from . import lr
