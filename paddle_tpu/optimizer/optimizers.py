"""Concrete optimizers (ref: python/paddle/optimizer/{sgd,momentum,adam,
adamw,lamb,rmsprop,adagrad}.py (U)). Each _update is a pure array function —
the single source of truth reused by eager step(), jit train steps, and the
ZeRO-sharded distributed optimizers. The reference's fused/multi_tensor CUDA
paths (fused_adam, SURVEY.md §2.1 N4) are unnecessary: XLA fuses the whole
update chain into one kernel per parameter (and the jitted train step fuses
across parameters)."""

from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer, _apply_l2


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._data)}

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            update = grad + self._momentum * v
        else:
            update = v
        return param - lr * update, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data, self._init_acc)}

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        m = state["moment"] + jnp.square(grad)
        return param - lr * grad / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class Adadelta(Optimizer):
    """Adadelta (ref: python/paddle/optimizer/adadelta.py (U)): step size
    from the ratio of running RMS of updates to running RMS of grads."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._rho = rho
        self._multi_precision = multi_precision

    def _init_state(self, p):
        st = {
            "avg_squared_grad": jnp.zeros(p._data.shape, jnp.float32),
            "avg_squared_update": jnp.zeros(p._data.shape, jnp.float32),
        }
        if self._multi_precision and p._data.dtype != jnp.float32:
            st["master_weight"] = p._data.astype(jnp.float32)
        return st

    def _update(self, param, grad, state, lr):
        # decay against the f32 master weight when present, not the
        # quantized bf16 param (mirrors Adam's _adam_math)
        g32 = _apply_l2(grad, state.get("master_weight", param),
                        self._cur_wd).astype(jnp.float32)
        eg = self._rho * state["avg_squared_grad"] \
            + (1 - self._rho) * jnp.square(g32)
        upd = -jnp.sqrt((state["avg_squared_update"] + self._epsilon)
                        / (eg + self._epsilon)) * g32
        eu = self._rho * state["avg_squared_update"] \
            + (1 - self._rho) * jnp.square(upd)
        p32 = state.get("master_weight", param).astype(jnp.float32) + lr * upd
        new_state = {"avg_squared_grad": eg, "avg_squared_update": eu}
        if "master_weight" in state:
            new_state["master_weight"] = p32
        return p32.astype(param.dtype), new_state


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._data), "velocity": jnp.zeros_like(p._data)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._data)
        return st

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(grad)
        new_state = dict(state, mean_square=ms)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        v = self._momentum * state["velocity"] + lr * grad / denom
        new_state["velocity"] = v
        return param - v, new_state


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision

    def _init_state(self, p):
        st = {
            "moment1": jnp.zeros_like(p._data, dtype=jnp.float32),
            "moment2": jnp.zeros_like(p._data, dtype=jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }
        if self._multi_precision and p._data.dtype != jnp.float32:
            st["master_weight"] = p._data.astype(jnp.float32)
        return st

    def _adam_math(self, param, grad, state, lr, decoupled_wd=0.0, coupled_l2=0.0):
        master = state.get("master_weight", param)
        p32 = master.astype(jnp.float32)
        g32 = grad.astype(jnp.float32)
        if coupled_l2:
            g32 = g32 + coupled_l2 * p32
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        if decoupled_wd:
            p32 = p32 * (1 - lr * decoupled_wd)
        p32 = p32 - lr * m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        new_state = dict(state, moment1=m1, moment2=m2, beta1_pow=b1p, beta2_pow=b2p)
        if "master_weight" in state:
            new_state["master_weight"] = p32
        return p32.astype(param.dtype), new_state

    def _update(self, param, grad, state, lr):
        return self._adam_math(param, grad, state, lr, coupled_l2=self._cur_wd)


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) else float(
            getattr(weight_decay, "_coeff", 0.01))
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decay_for(self, p):
        if self._decay_exempt(p):
            return 0.0
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(self._param_names[id(p)]):
            return 0.0
        return self._coeff

    def _update_raw(self, p, param, grad, state, lr):
        # decoupled decay + per-param lr ratio ride this hook so the eager
        # step() and the compiled TrainStep path stay identical (dtype
        # pinning happens in the base _update_for)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        return self._adam_math(param, grad, state, lr,
                               decoupled_wd=self._decay_for(p))


class Adamax(Adam):
    def _init_state(self, p):
        return {
            "moment": jnp.zeros_like(p._data, dtype=jnp.float32),
            "inf_norm": jnp.zeros_like(p._data, dtype=jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, param, grad, state, lr):
        g32 = _apply_l2(grad.astype(jnp.float32), param.astype(jnp.float32), self._cur_wd)
        b1p = state["beta1_pow"] * self._beta1
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32) + 1e-12)
        p32 = param.astype(jnp.float32) - (lr / (1 - b1p)) * m / (u + self._epsilon)
        return p32.astype(param.dtype), dict(state, moment=m, inf_norm=u, beta1_pow=b1p)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # Lamb's decay rides the base coupled-wd machinery so
        # no_weight_decay / per-param regularizers exempt it like everywhere
        # else; _cur_wd then carries the effective per-param coefficient
        self._weight_decay = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _decay_exempt(self, p):
        if super()._decay_exempt(p):
            return True
        return self._exclude_fn is not None and bool(self._exclude_fn(p))

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._data, dtype=jnp.float32),
            "moment2": jnp.zeros_like(p._data, dtype=jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, param, grad, state, lr):
        p32 = param.astype(jnp.float32)
        g32 = grad.astype(jnp.float32)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        r = m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        update = r + self._cur_wd * p32
        w_norm = jnp.linalg.norm(p32.reshape(-1))
        u_norm = jnp.linalg.norm(update.reshape(-1))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        p32 = p32 - lr * trust * update
        return p32.astype(param.dtype), dict(state, moment1=m1, moment2=m2,
                                             beta1_pow=b1p, beta2_pow=b2p)


class Rprop(Optimizer):
    """Resilient backprop (ref: python/paddle/optimizer/rprop.py (U)):
    per-element step sizes grown/shrunk by gradient sign agreement."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _init_state(self, p):
        return {
            "prev_grad": jnp.zeros_like(p._data, dtype=jnp.float32),
            "step_size": jnp.full_like(p._data, float(self.get_lr()),
                                       dtype=jnp.float32),
        }

    def _update(self, param, grad, state, lr):
        g32 = grad.astype(jnp.float32)
        sign = jnp.sign(g32 * state["prev_grad"])
        step = jnp.where(sign > 0, state["step_size"] * self._eta_pos,
                         jnp.where(sign < 0,
                                   state["step_size"] * self._eta_neg,
                                   state["step_size"]))
        step = jnp.clip(step, self._lr_min, self._lr_max)
        # on sign flip, skip the update and zero the remembered grad
        g_eff = jnp.where(sign < 0, 0.0, g32)
        p32 = param.astype(jnp.float32) - jnp.sign(g_eff) * step
        return p32.astype(param.dtype), {"prev_grad": g_eff,
                                         "step_size": step}


class ASGD(Optimizer):
    """Averaged SGD (ref: python/paddle/optimizer/asgd.py (U)): each step
    applies the mean of the last ``batch_num`` gradients, tracked with a
    running sum ``d`` plus a circular buffer ``y`` of the contributing
    gradients (the reference's d/y accumulator scheme)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._batch_num = max(1, int(batch_num))

    def _init_state(self, p):
        shape = tuple(p._data.shape)
        return {
            "d": jnp.zeros(shape, jnp.float32),
            "y": jnp.zeros((self._batch_num,) + shape, jnp.float32),
            "step": jnp.zeros((), jnp.float32),
        }

    def _update(self, param, grad, state, lr):
        from jax import lax
        g32 = _apply_l2(grad, param, self._cur_wd).astype(jnp.float32)
        n = state["y"].shape[0]
        idx = jnp.mod(state["step"], float(n)).astype(jnp.int32)
        oldest = lax.dynamic_index_in_dim(state["y"], idx, keepdims=False)
        d = state["d"] - oldest + g32
        y = lax.dynamic_update_index_in_dim(
            state["y"], g32[None], idx, axis=0)
        count = jnp.minimum(state["step"] + 1.0, float(n))
        p32 = param.astype(jnp.float32) - lr * d / count
        return p32.astype(param.dtype), {"d": d, "y": y,
                                         "step": state["step"] + 1.0}


class NAdam(Adam):
    """Adam with Nesterov momentum (ref: python/paddle/optimizer/nadam.py
    (U))."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision=multi_precision)
        self._psi = momentum_decay

    def _init_state(self, p):
        st = super()._init_state(p)
        st["mu_product"] = jnp.ones((), jnp.float32)
        st["step"] = jnp.zeros((), jnp.float32)
        return st

    def _update(self, param, grad, state, lr):
        p32 = state.get("master_weight", param).astype(jnp.float32)
        g32 = grad.astype(jnp.float32)
        if self._cur_wd:
            g32 = g32 + self._cur_wd * p32
        # explicit f32 step counter: recovering it from beta2_pow underflows
        # to step=inf once beta2_pow hits f32 zero (~88k steps at beta2=.999)
        step = state["step"] + 1.0
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (step * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((step + 1) * self._psi))
        mu_prod = state["mu_product"] * mu_t
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        b2p = state["beta2_pow"] * self._beta2
        m2_hat = m2 / (1 - b2p)
        m1_bar = (mu_t1 * m1 / (1 - mu_prod * mu_t1)
                  + (1 - mu_t) * g32 / (1 - mu_prod))
        p32 = p32 - lr * m1_bar / (jnp.sqrt(m2_hat) + self._epsilon)
        new_state = dict(
            state, moment1=m1, moment2=m2,
            beta1_pow=state["beta1_pow"] * self._beta1, beta2_pow=b2p,
            mu_product=mu_prod, step=step)
        if "master_weight" in state:
            new_state["master_weight"] = p32
        return p32.astype(param.dtype), new_state


class RAdam(Adam):
    """Rectified Adam (ref: python/paddle/optimizer/radam.py (U))."""

    def _init_state(self, p):
        st = super()._init_state(p)
        st["step"] = jnp.zeros((), jnp.float32)
        return st

    def _update(self, param, grad, state, lr):
        p32 = state.get("master_weight", param).astype(jnp.float32)
        g32 = grad.astype(jnp.float32)
        if self._cur_wd:
            g32 = g32 + self._cur_wd * p32
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        # explicit f32 step counter (see NAdam): log(b2p) blows up once
        # beta2_pow underflows, sending rho_t to NaN and silently pinning
        # the un-rectified branch for the rest of training
        step = state["step"] + 1.0
        rho_inf = 2.0 / (1 - self._beta2) - 1.0
        rho_t = rho_inf - 2.0 * step * b2p / (1 - b2p)
        m1_hat = m1 / (1 - b1p)
        rect = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                        / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t,
                                      1e-12))
        adaptive = rect * m1_hat / (jnp.sqrt(m2 / (1 - b2p)) + self._epsilon)
        sgd_like = m1_hat
        upd = jnp.where(rho_t > 5.0, adaptive, sgd_like)
        p32 = p32 - lr * upd
        new_state = dict(state, moment1=m1, moment2=m2, beta1_pow=b1p,
                         beta2_pow=b2p, step=step)
        if "master_weight" in state:
            new_state["master_weight"] = p32
        return p32.astype(param.dtype), new_state
