"""Concrete optimizers (ref: python/paddle/optimizer/{sgd,momentum,adam,
adamw,lamb,rmsprop,adagrad}.py (U)). Each _update is a pure array function —
the single source of truth reused by eager step(), jit train steps, and the
ZeRO-sharded distributed optimizers. The reference's fused/multi_tensor CUDA
paths (fused_adam, SURVEY.md §2.1 N4) are unnecessary: XLA fuses the whole
update chain into one kernel per parameter (and the jitted train step fuses
across parameters)."""

from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer, _apply_l2


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._data)}

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            update = grad + self._momentum * v
        else:
            update = v
        return param - lr * update, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data, self._init_acc)}

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        m = state["moment"] + jnp.square(grad)
        return param - lr * grad / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._data), "velocity": jnp.zeros_like(p._data)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._data)
        return st

    def _update(self, param, grad, state, lr):
        grad = _apply_l2(grad, param, self._cur_wd)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(grad)
        new_state = dict(state, mean_square=ms)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        v = self._momentum * state["velocity"] + lr * grad / denom
        new_state["velocity"] = v
        return param - v, new_state


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision

    def _init_state(self, p):
        st = {
            "moment1": jnp.zeros_like(p._data, dtype=jnp.float32),
            "moment2": jnp.zeros_like(p._data, dtype=jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }
        if self._multi_precision and p._data.dtype != jnp.float32:
            st["master_weight"] = p._data.astype(jnp.float32)
        return st

    def _adam_math(self, param, grad, state, lr, decoupled_wd=0.0, coupled_l2=0.0):
        master = state.get("master_weight", param)
        p32 = master.astype(jnp.float32)
        g32 = grad.astype(jnp.float32)
        if coupled_l2:
            g32 = g32 + coupled_l2 * p32
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        if decoupled_wd:
            p32 = p32 * (1 - lr * decoupled_wd)
        p32 = p32 - lr * m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        new_state = dict(state, moment1=m1, moment2=m2, beta1_pow=b1p, beta2_pow=b2p)
        if "master_weight" in state:
            new_state["master_weight"] = p32
        return p32.astype(param.dtype), new_state

    def _update(self, param, grad, state, lr):
        return self._adam_math(param, grad, state, lr, coupled_l2=self._cur_wd)


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) else float(
            getattr(weight_decay, "_coeff", 0.01))
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _decay_for(self, p):
        if self._decay_exempt(p):
            return 0.0
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(self._param_names[id(p)]):
            return 0.0
        return self._coeff

    def _update_raw(self, p, param, grad, state, lr):
        # decoupled decay + per-param lr ratio ride this hook so the eager
        # step() and the compiled TrainStep path stay identical (dtype
        # pinning happens in the base _update_for)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        return self._adam_math(param, grad, state, lr,
                               decoupled_wd=self._decay_for(p))


class Adamax(Adam):
    def _init_state(self, p):
        return {
            "moment": jnp.zeros_like(p._data, dtype=jnp.float32),
            "inf_norm": jnp.zeros_like(p._data, dtype=jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, param, grad, state, lr):
        g32 = _apply_l2(grad.astype(jnp.float32), param.astype(jnp.float32), self._cur_wd)
        b1p = state["beta1_pow"] * self._beta1
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32) + 1e-12)
        p32 = param.astype(jnp.float32) - (lr / (1 - b1p)) * m / (u + self._epsilon)
        return p32.astype(param.dtype), dict(state, moment=m, inf_norm=u, beta1_pow=b1p)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # Lamb's decay rides the base coupled-wd machinery so
        # no_weight_decay / per-param regularizers exempt it like everywhere
        # else; _cur_wd then carries the effective per-param coefficient
        self._weight_decay = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _decay_exempt(self, p):
        if super()._decay_exempt(p):
            return True
        return self._exclude_fn is not None and bool(self._exclude_fn(p))

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._data, dtype=jnp.float32),
            "moment2": jnp.zeros_like(p._data, dtype=jnp.float32),
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def _update(self, param, grad, state, lr):
        p32 = param.astype(jnp.float32)
        g32 = grad.astype(jnp.float32)
        b1p = state["beta1_pow"] * self._beta1
        b2p = state["beta2_pow"] * self._beta2
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        r = m1_hat / (jnp.sqrt(m2_hat) + self._epsilon)
        update = r + self._cur_wd * p32
        w_norm = jnp.linalg.norm(p32.reshape(-1))
        u_norm = jnp.linalg.norm(update.reshape(-1))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        p32 = p32 - lr * trust * update
        return p32.astype(param.dtype), dict(state, moment1=m1, moment2=m2,
                                             beta1_pow=b1p, beta2_pow=b2p)
