"""LBFGS (ref: python/paddle/optimizer/lbfgs.py (U)).

Closure-driven quasi-Newton: two-loop recursion over an (s, y) history kept
host-side, the vector math in jax. The reference's step(closure) contract is
preserved — closure re-evaluates loss and grads; line_search_fn='strong_wolfe'
uses a backtracking search satisfying Armijo + curvature."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape
from .optimizer import Optimizer


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = max_iter
        self._max_eval = max_eval or max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = history_size
        self._line_search = line_search_fn
        self._s, self._y = [], []
        self._prev_flat_grad = None

    # ---- flat vector helpers ----------------------------------------
    def _params(self):
        return [p for p in self._parameter_list if p.trainable]

    def _flat(self, arrays):
        return jnp.concatenate([a.reshape(-1).astype(jnp.float32)
                                for a in arrays])

    def _flat_params(self):
        return self._flat([p._data for p in self._params()])

    def _flat_grads(self):
        return self._flat([p.grad._data if p.grad is not None
                           else jnp.zeros_like(p._data)
                           for p in self._params()])

    def _assign(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            p._data = flat[off:off + n].reshape(p.shape).astype(p._data.dtype)
            off += n

    def _direction(self, g):
        """Two-loop recursion over the stored (s, y) pairs."""
        q = -g
        alphas = []
        for s, y in reversed(list(zip(self._s, self._y))):
            rho = 1.0 / jnp.maximum(jnp.dot(y, s), 1e-10)
            a = rho * jnp.dot(s, q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        if self._s:
            s, y = self._s[-1], self._y[-1]
            q = q * (jnp.dot(s, y) / jnp.maximum(jnp.dot(y, y), 1e-10))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.dot(y, q)
            q = q + s * (a - b)
        return q

    def _eval(self, closure):
        for p in self._params():
            p.clear_grad()
        loss = closure()
        return float(loss), self._flat_grads()

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that recomputes "
                             "the loss and calls backward()")
        lr = self.get_lr()
        loss, g = self._eval(closure)
        evals = 1
        for _ in range(self._max_iter):
            if float(jnp.max(jnp.abs(g))) <= self._tol_grad:
                break
            d = self._direction(g)
            x0 = self._flat_params()
            gtd = float(jnp.dot(g, d))
            if gtd > -1e-15:  # not a descent direction: reset history
                self._s, self._y = [], []
                d = -g
                gtd = float(jnp.dot(g, d))
            t = lr
            if self._line_search == "strong_wolfe":
                c1, c2 = 1e-4, 0.9
                ok = False
                for _ls in range(20):
                    self._assign(x0 + t * d)
                    new_loss, new_g = self._eval(closure)
                    evals += 1
                    if new_loss <= loss + c1 * t * gtd and \
                            abs(float(jnp.dot(new_g, d))) <= -c2 * gtd:
                        ok = True
                        break
                    t *= 0.5
                    if evals >= self._max_eval:
                        break
                if not ok:
                    self._assign(x0 + t * d)
                    new_loss, new_g = self._eval(closure)
                    evals += 1
            else:
                self._assign(x0 + t * d)
                new_loss, new_g = self._eval(closure)
                evals += 1
            s = t * d
            yv = new_g - g
            if float(jnp.dot(s, yv)) > 1e-10:
                self._s.append(s)
                self._y.append(yv)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            if abs(new_loss - loss) < self._tol_change:
                loss, g = new_loss, new_g
                break
            loss, g = new_loss, new_g
            if evals >= self._max_eval:
                break
        self._step_count += 1
        return Tensor(jnp.asarray(loss))
