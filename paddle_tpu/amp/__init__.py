from .auto_cast import auto_cast, amp_guard, decorate, white_list, black_list, is_bf16_supported, is_float16_supported
is_bfloat16_supported = is_bf16_supported
from .grad_scaler import GradScaler, AmpScaler, OptimizerState
from . import debugging

autocast = auto_cast
