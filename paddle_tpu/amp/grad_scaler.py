"""GradScaler: dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py (U)).

Needed for fp16 parity; bf16 training on TPU normally runs unscaled (the
default `enable` honors that — scaling is a no-op unless fp16 is in play or
the user forces it)."""

from __future__ import annotations

import enum

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape


class OptimizerState(enum.Enum):
    INIT = 0
    UNSCALED = 1
    STEPPED = 2


class AmpScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0**16, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._state = OptimizerState.INIT

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = optimizer._parameter_list
        inv = 1.0 / self._scale
        found = jnp.zeros((), jnp.bool_)
        with _tape.no_grad():
            for p in params:
                if p.grad is None:
                    continue
                g = p.grad._data.astype(jnp.float32) * inv
                found = found | ~jnp.all(jnp.isfinite(g))
                p.grad._data = g.astype(p.grad._data.dtype)
        self._found_inf = bool(found)
        self._state = OptimizerState.UNSCALED

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if self._state != OptimizerState.UNSCALED:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._state = OptimizerState.STEPPED

    def update(self):
        if not self._enable or not self._dynamic:
            self._state = OptimizerState.INIT
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._state = OptimizerState.INIT

    def minimize(self, optimizer, loss):
        scaled = self.scale(loss)
        scaled.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    # -------- introspection / state --------
    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def get_incr_ratio(self):
        return self._incr_ratio

    def set_incr_ratio(self, v):
        self._incr_ratio = v

    def get_decr_ratio(self):
        return self._decr_ratio

    def set_decr_ratio(self, v):
        self._decr_ratio = v

    def get_incr_every_n_steps(self):
        return self._incr_every

    def set_incr_every_n_steps(self, v):
        self._incr_every = v

    def get_decr_every_n_nan_or_inf(self):
        return self._decr_every

    def set_decr_every_n_nan_or_inf(self, v):
        self._decr_every = v

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every,
            "decr_every_n_nan_or_inf": self._decr_every,
            "incr_count": self._good_steps,
            "decr_count": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._incr_ratio = state.get("incr_ratio", self._incr_ratio)
        self._decr_ratio = state.get("decr_ratio", self._decr_ratio)
        self._incr_every = state.get("incr_every_n_steps", self._incr_every)
        self._decr_every = state.get("decr_every_n_nan_or_inf", self._decr_every)
        self._good_steps = state.get("incr_count", 0)
        self._bad_steps = state.get("decr_count", 0)


class GradScaler(AmpScaler):
    pass
