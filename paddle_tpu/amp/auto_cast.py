"""AMP autocast (ref: python/paddle/amp/auto_cast.py (U) — O1 white/black
op lists, O2 pure-half with master weights).

TPU-native stance: bfloat16 is the native half type (MXU runs bf16 natively;
no loss scaling needed for bf16). The white/black list mechanism is preserved:
whitelisted ops (matmul/conv) cast inputs to the amp dtype inside `apply()`,
blacklisted ops (softmax/norms/reductions) compute in fp32 — same split the
reference encodes in its AMP lists.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.dtype import to_jax_dtype

# mirror of the reference's default O1 lists (ops named by our op names)
WHITE_LIST = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "einsum", "mm", "bmm", "mv",
    "flash_attention", "scaled_dot_product_attention",
}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "layer_norm", "batch_norm", "group_norm", "instance_norm",
    "rms_norm", "logsumexp", "erf", "erfinv", "pow", "log_softmax",
    "sync_batch_norm", "norm", "var", "std",
}


def white_list():
    return {"float16": {"O1": WHITE_LIST, "O2": WHITE_LIST}, "bfloat16": {"O1": WHITE_LIST, "O2": WHITE_LIST}}


def black_list():
    return {"float16": {"O1": BLACK_LIST, "O2": BLACK_LIST}, "bfloat16": {"O1": BLACK_LIST, "O2": BLACK_LIST}}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_STATE = _AmpState()


def amp_state():
    return _STATE


def amp_dtype_for(op_name: str):
    """Called by core.op_call: returns the compute dtype for op_name under the
    active autocast scope, or None for 'leave as is'."""
    if not _STATE.enabled:
        return None
    if not op_name:
        # unnamed ops (misc linalg/search helpers) are never auto-cast — even
        # under O2 — since their dtype support is op-specific
        return None
    if op_name in _STATE.custom_black or op_name in BLACK_LIST:
        return jnp.float32
    if _STATE.level == "O2":
        return _STATE.dtype
    if op_name in _STATE.custom_white or op_name in WHITE_LIST:
        return _STATE.dtype
    return None


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    from ..core import op_call as _op_call

    prev = (_STATE.enabled, _STATE.dtype, _STATE.level, _STATE.custom_white, _STATE.custom_black)
    prev_hook = _op_call._AMP_LOOKUP
    _STATE.enabled = enable
    _STATE.dtype = to_jax_dtype(dtype)
    _STATE.level = level
    _STATE.custom_white = set(custom_white_list or ())
    _STATE.custom_black = set(custom_black_list or ())
    # the dispatch hook is installed only while a scope is active, so eager
    # dispatch outside autocast stays a single `is None` check
    _op_call.set_amp_lookup(amp_dtype_for)
    try:
        yield
    finally:
        (_STATE.enabled, _STATE.dtype, _STATE.level, _STATE.custom_white, _STATE.custom_black) = prev
        _op_call.set_amp_lookup(prev_hook)


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast float params to the amp dtype; Adam-family
    optimizers keep fp32 master weights automatically (multi_precision path)."""
    jd = to_jax_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    for m in model_list:
        if level == "O2":
            m.to(dtype=jd)
    opt_single = optimizers is not None and not isinstance(optimizers, (list, tuple))
    opt_list = [] if optimizers is None else ([optimizers] if opt_single else list(optimizers))
    for o in opt_list:
        if hasattr(o, "_multi_precision"):
            o._multi_precision = True
    if optimizers is None:
        return models
    return (model_list[0] if single else model_list), (opt_list[0] if opt_single else opt_list)


def is_bf16_supported():
    return True


def is_float16_supported():
    return True


