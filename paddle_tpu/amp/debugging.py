"""paddle.amp.debugging parity (ref: python/paddle/amp/debugging.py (U)):
nan/inf checking. TPU-native backing: jax debug_nans plus an explicit
tensor-checker API."""

from __future__ import annotations

import contextlib
import enum

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir


_CONFIG = [None]


def enable_tensor_checker(config: TensorCheckerConfig):
    _CONFIG[0] = config
    if config.enable:
        jax.config.update("jax_debug_nans", True)


def disable_tensor_checker():
    _CONFIG[0] = None
    jax.config.update("jax_debug_nans", False)


def check_numerics(tensor, op_type="", var_name="", debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT):
    data = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    n_nan = int(jnp.sum(jnp.isnan(data)))
    n_inf = int(jnp.sum(jnp.isinf(data)))
    if (n_nan or n_inf) and debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        raise FloatingPointError(
            f"check_numerics: {op_type}/{var_name} has {n_nan} nan, {n_inf} inf"
        )
    from ..tensor.creation import _as_t

    return Tensor(jnp.asarray([n_nan])), Tensor(jnp.asarray([n_inf]))


@contextlib.contextmanager
def collect_operator_stats():
    yield


def enable_operator_stats_collection():
    pass


def disable_operator_stats_collection():
    pass


def compare_accuracy(dump_path, another_dump_path, output_filename, loss_scale=1, dump_all_tensors=False):
    raise NotImplementedError("accuracy comparison dumps are not supported on the TPU build")
