"""paddle.inference parity (ref: AnalysisPredictor + the handle-based
Tensor API, SURVEY.md §2.1 N19 — the TensorRT/IR-optimization engine is
out of core scope; XLA fills that role). The Predictor here is real: it
loads a jit-saved StableHLO artifact and serves it through the
reference's workflow —

    config = Config("model.pdmodel", "model.pdiparams")
    predictor = create_predictor(config)
    inp = predictor.get_input_handle(predictor.get_input_names()[0])
    inp.copy_from_cpu(batch_np)
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0])
    result = out.copy_to_cpu()
"""

from __future__ import annotations

import numpy as np


class Config:
    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path

    # accepted-for-parity toggles: device/IR choices are XLA's business
    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_mkldnn(self):
        pass

    def disable_glog_info(self):
        pass


class InferTensor:
    """Handle for one predictor input/output (ref: paddle.inference.Tensor):
    host-side staging with copy_from_cpu/copy_to_cpu."""

    def __init__(self, name):
        self.name = name
        self._arr = None

    def copy_from_cpu(self, arr):
        self._arr = np.ascontiguousarray(arr)

    def copy_to_cpu(self):
        if self._arr is None:
            raise RuntimeError(f"tensor {self.name!r} holds no data")
        return np.asarray(self._arr)

    def reshape(self, shape):
        if self._arr is not None:
            self._arr = self._arr.reshape(shape)

    def shape(self):
        return [] if self._arr is None else list(self._arr.shape)


class Predictor:
    def __init__(self, config):
        from ..jit.api import load as jit_load

        prefix = config.model_path
        if prefix and prefix.endswith(".pdmodel"):
            prefix = prefix[: -len(".pdmodel")]
        self._layer = jit_load(prefix, params_path=config.params_path)
        names = getattr(self._layer, "_input_names", None) or []
        self._inputs = {n: InferTensor(n) for n in names}
        # persistent output handles, known BEFORE the first run (like the
        # reference), under the REAL fetch names persisted in the artifact
        # (save_inference_model round-trips fetch-var names; jit.save
        # defaults to output_{i})
        out_names = getattr(self._layer, "_output_names", None) or [
            f"output_{i}"
            for i in range(len(self._layer._exported.out_avals))]
        self._output_order = list(out_names)
        self._outputs = {n: InferTensor(n) for n in out_names}

    # ---------------- handle API (the reference workflow)
    def get_input_names(self):
        return list(self._inputs)

    def get_input_handle(self, name):
        if name not in self._inputs:
            raise KeyError(f"unknown input {name!r}; inputs are "
                           f"{list(self._inputs)}")
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs)

    def get_output_handle(self, name):
        if name not in self._outputs:
            raise KeyError(f"unknown output {name!r}; outputs are "
                           f"{list(self._outputs)}")
        return self._outputs[name]

    # ---------------- execution
    def run(self, inputs=None):
        """Handle mode: run() after copy_from_cpu on every input handle.
        Legacy mode: run([np_arrays...]) returns a list of np arrays."""
        if inputs is not None:
            outs = self._layer(*inputs)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            return [np.asarray(o._data) for o in outs]
        missing = [n for n, h in self._inputs.items() if h._arr is None]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        outs = self._layer(*[self._inputs[n]._arr for n in self._inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        if len(outs) != len(self._output_order):
            raise RuntimeError(
                f"program returned {len(outs)} outputs but the artifact "
                f"declares {len(self._output_order)}")
        for name, o in zip(self._output_order, outs):
            self._outputs[name]._arr = np.asarray(o._data)  # in place:
            # previously fetched handles keep observing fresh results
        return True


def create_predictor(config):
    return Predictor(config)


def create_llm_engine(model, mesh_shape=None, tp=None, **config_kwargs):
    """Predictor-style entry point for LLM serving: wrap a CausalLM Layer
    in the continuous-batching `paddle_tpu.serving.Engine` (the TPU
    rebuild of the reference's AnalysisPredictor + fused_multi_transformer
    decode path). Keyword args populate `serving.EngineConfig`
    (num_slots, max_seq_len, min_prefill_bucket, cache_dtype,
    max_horizon — the ceiling for horizon-scanned fused decode, where
    one compiled ``lax.scan`` dispatch advances every slot up to
    ``max_horizon`` tokens with a single host sync per horizon;
    prefix_block_size / prefix_cache_bytes — the shared-prefix KV cache
    that reuses cached prompt blocks instead of recomputing them, 0
    block size disables; reorder_window — how far admission may
    co-bucket queued requests into one batched prefill dispatch without
    starving FIFO order; spec_k — speculative decoding draft width:
    each decode step self-drafts up to ``spec_k`` tokens per lane from
    an n-gram lookup over the lane's own history and verifies all
    ``spec_k + 1`` positions in one forward, emitting every accepted
    token — outputs stay bitwise-equal to ``spec_k=0``, 0 disables;
    spec_adaptive — per-lane acceptance-rate gating that stops drafting
    for lanes where speculation is not paying, so incompressible
    streams keep plain-decode throughput;
    weight_dtype — "int8" PTQ-quantizes every Linear weight at engine
    build (per-output-channel absmax scales) and dequantizes inline in
    the compiled programs, shrinking the per-step weight stream ~2x at
    bf16 / ~4x at f32 while matmul math stays fp — greedy outputs may
    legitimately differ from fp within quantization tolerance;
    kv_cache_dtype — "int8" stores paged-KV blocks as int8 with one f32
    scale per written token beside the block table (quantize at
    append/COW, dequantize after the attention gather), cutting decode
    KV traffic ~4x at f32 and ~2x-ing how many sequences fit a fixed
    pool byte budget; None for either knob keeps the fp path
    bitwise-untouched;
    request_tracing / flight_recorder_capacity — per-request lifecycle
    flight records (queued/prefill/decode/preempt/finish events with
    monotonic timestamps) retained for all live plus the last-N
    finished requests, inspectable via ``engine.recorder`` or the
    ``/debug/requests`` endpoint;
    slo_ttft_s / slo_tpot_s / slo_abort_rate (+ slo_target,
    slo_fast_window, slo_slow_window) — declared SLO objectives over
    step-sized rolling windows with multi-window burn-rate health,
    published as ``slo.*`` gauges and driving ``/readyz``;
    telemetry_port — start an HTTP telemetry endpoint (``/metrics``,
    ``/healthz``, ``/readyz``, ``/debug/requests``, ``/debug/slo``,
    ``/trace``) on a background thread at engine construction, 0 for an
    ephemeral port, stopped by ``engine.close()``;
    grammar_max_states / grammar_vocab / grammar_forced_drafting —
    structured generation: ``grammar_max_states=N`` (rows of the
    device-resident DFA slab; 0, the default, disables and keeps every
    compiled program grammar-free) plus ``grammar_vocab`` (token-id ->
    string list the grammar compiler crossproducts against) let
    ``engine.submit(..., grammar=...)`` take a regex string, a
    JSON-schema dict, or a ``GrammarSpec`` — constrained lanes emit
    only grammar-legal tokens (EOS exactly at accept states; requires
    ``eos_token_id``), stay bitwise batched-vs-sequential, and share
    the compiled program with free lanes via the accept-all sentinel;
    ``grammar_forced_drafting`` (default True, needs ``spec_k > 0``)
    drafts sole-legal-token chains ahead of n-gram proposals so JSON
    skeleton punctuation is accepted at draft price;
    ``grammar_cache_keep`` (default 8) bounds the host compile cache —
    DFAs stay pinned while a live request references them, plus this
    many retired entries kept LRU so repeat grammars skip
    recompilation).

    ``mesh_shape`` / ``tp`` pick the sharded engine: ``tp=N`` (or
    ``mesh_shape=(1, N)``; both knobs must agree when both are given)
    returns a ``serving.sharded.MeshEngine`` running tensor-parallel
    over N devices with the mesh-sharded paged KV pool — same API, same
    knobs, output bitwise-equal to the single-chip engine.  ``tp=1``
    (or both None, the default) returns the plain single-chip
    ``Engine``; dp > 1 raises (reserved for disaggregated
    prefill/decode)."""
    from ..serving import Engine, EngineConfig
    from ..serving.sharded import MeshEngine

    if mesh_shape is None and tp is None:
        return Engine(model, EngineConfig(**config_kwargs))
    shape = MeshEngine._norm_mesh_knob(mesh_shape, tp)
    if shape == (1, 1):
        return Engine(model, EngineConfig(**config_kwargs))
    return MeshEngine(model, EngineConfig(**config_kwargs),
                      mesh_shape=shape)


# reference module aliases
Tensor = InferTensor
PrecisionType = type("PrecisionType", (), {"Float32": 0, "Half": 1,
                                          "Bfloat16": 2, "Int8": 3})
