"""paddle.inference parity (ref: AnalysisPredictor, SURVEY.md §2.1 N19 —
declared out of core scope there; this shim serves the API so inference
scripts can load jit-saved StableHLO artifacts)."""

from __future__ import annotations


class Config:
    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path

    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self):
        pass


class Predictor:
    def __init__(self, config):
        from ..jit.api import load as jit_load

        prefix = config.model_path
        if prefix and prefix.endswith(".pdmodel"):
            prefix = prefix[: -len(".pdmodel")]
        self._layer = jit_load(prefix)

    def run(self, inputs):
        outs = self._layer(*inputs)
        return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def create_predictor(config):
    return Predictor(config)
