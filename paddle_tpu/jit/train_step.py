"""TrainStep: the whole training step (forward + backward + optimizer) as ONE
compiled XLA program.

This is the TPU performance path that replaces the reference's
to_static-training + CINN pipeline (SURVEY.md §3.4): parameters and optimizer
state are functionalized into explicit pytree arguments (donated, so updates
are in-place in HBM), the tape runs at trace time, and XLA fuses fwd+bwd+adam
across the step. The same object also powers fleet.distributed_model's jitted
path, where `shardings` place params/batch on a mesh.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape
from ..core import random_state
from ..observability import metrics as _obs_metrics

# NOTE: jax dispatch is async — step_seconds is host wall time per
# dispatched step, which converges to true step time whenever the caller
# consumes the loss (float()) each step, as Model.fit and every trainer
# in this repo do
_STEP_SECONDS = _obs_metrics.histogram(
    "train.step_seconds", "TrainStep wall seconds per compiled step")
_STEP_IPS = _obs_metrics.histogram(
    "train.ips", "TrainStep items (batch rows) per second")
_STEP_COUNT = _obs_metrics.counter(
    "train.steps", "compiled optimizer steps taken")

_LAYOUT_API = False  # unresolved sentinel (None = resolved, unavailable)


def _layout_api():
    """Resolve the compiled-layout API once: jax>=0.5 spells it
    Format/Layout + compiled.input_formats + arr.format; jax 0.4 spells
    the same machinery Layout/DeviceLocalLayout + compiled.input_layouts
    + arr.layout. Returns (AUTO_spec, compiled_attr, leaf_attr), or None
    on a jax with neither — the AUTO-layout path then disables itself
    instead of raising ImportError at the first step (r5: the hapi/jit
    suites went down wholesale on jax 0.4.37)."""
    global _LAYOUT_API
    if _LAYOUT_API is False:
        try:
            from jax.experimental.layout import Format, Layout

            _LAYOUT_API = (Format(Layout.AUTO), "input_formats", "format")
        except ImportError:
            try:
                from jax.experimental.layout import (
                    DeviceLocalLayout, Layout,
                )

                _LAYOUT_API = (Layout(DeviceLocalLayout.AUTO),
                               "input_layouts", "layout")
            except ImportError:
                _LAYOUT_API = None
    return _LAYOUT_API


class TrainStep:
    def __init__(self, model, loss_fn, optimizer, scaler=None, donate=True,
                 mesh=None, in_shardings=None, has_aux=False,
                 auto_layout=None):
        """loss_fn(model, *batch_tensors) -> loss Tensor (scalar), or with
        has_aux=True -> (loss, aux) where aux is a Tensor/tuple of Tensors
        returned alongside the loss (e.g. network outputs for metric
        updates — ref Model.fit reports metrics every train batch).

        auto_layout (default: on for single-device steps): compile with
        compiler-CHOSEN input layouts (jax.experimental.layout AUTO) and
        re-lay the params/optimizer states out once to match. Without it,
        XLA must layout-copy big weights between the conv-preferred and
        the default parameter layout EVERY step (donated aliasing pins
        entry layout == exit layout): the r4 SD-UNet trace showed 40
        ms/step — 40% of device time — of f32 master-weight layout flips
        (benchmarks/profiles/unet_b4_r4.json)."""
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.scaler = scaler if (scaler is not None and scaler.is_enable()) else None
        self.donate = donate
        self.mesh = mesh
        self.has_aux = has_aux
        import os as _os

        env = _os.environ.get("PADDLE_TPU_AUTO_LAYOUT")
        if auto_layout is None and env is not None:
            auto_layout = env not in ("0", "false", "off")
        self.auto_layout = (auto_layout if auto_layout is not None
                            else mesh is None and in_shardings is None)
        if self.auto_layout and _layout_api() is None:
            self.auto_layout = False
        benv = _os.environ.get("PADDLE_TPU_UPDATE_BARRIER")
        # None = decide at build time from model size (see _build): the
        # barrier un-fuses dW matmuls from the optimizer update — a big
        # win for compute-dense models (BERT +17% on-chip) but a loss for
        # huge-parameter models whose grads then materialize to HBM
        # (860M-param SD-UNet −9%)
        self.update_barrier = (benv not in ("0", "false", "off")
                               if benv is not None else None)
        self._jitted = None
        self._compiled_cache = {}
        self._layout_owner = None   # cache entry whose AUTO layouts the
        # state arrays currently hold (see _run_auto)
        self._param_names = None
        self._buffer_names = None

    def _ensure_states(self):
        # materialize optimizer accumulators before tracing
        for p in self.optimizer._parameter_list:
            self.optimizer._state_for(p)

    def _build(self):
        if self.update_barrier is None:
            param_bytes = sum(
                p._data.size * p._data.dtype.itemsize
                for p in self.optimizer._parameter_list
                if hasattr(p, "_data"))
            self.update_barrier = param_bytes <= 512 * 1024 * 1024
        if self.auto_layout:
            # AUTO layouts lower from bare avals (no shardings): only safe
            # when every param lives on ONE device — a DistModel/pipeline
            # step whose params carry multi-device NamedShardings would be
            # silently gathered onto one chip
            for p in self.optimizer._parameter_list:
                sh = getattr(getattr(p, "_data", None), "sharding", None)
                if sh is not None and len(sh.device_set) > 1:
                    self.auto_layout = False
                    break
        step_fn = self._make_step_fn()
        # donated state buffers must exit with their ENTRY shardings or XLA
        # silently copies instead of aliasing ("Some donated buffers were
        # not usable" in the r4 dryrun tail — wasted HBM at scale): pin the
        # state outputs to the current state shardings when multi-device
        step_fn = self._constrain_state_outputs(step_fn)
        self._jitted = jax.jit(step_fn,
                               donate_argnums=(0, 2) if self.donate else ())

    _NOSH = object()          # "leave this leaf unconstrained" sentinel

    def _constrain_state_outputs(self, step_fn):
        from jax.sharding import NamedSharding

        sd = self.model.state_dict()
        opt = self.optimizer
        nosh = TrainStep._NOSH

        def sh_of(a):
            s = getattr(a, "sharding", None)
            return (s if isinstance(s, NamedSharding)
                    and len(s.device_set) > 1 else nosh)

        p_sh = [sh_of(sd[n]._data) for n in self._param_names]
        b_sh = [sh_of(sd[n]._data) for n in self._buffer_names]
        # _state_for is get-or-create: params outside the optimizer's
        # parameter list materialize their accumulator here
        o_sh = [jax.tree.map(sh_of, opt._state_for(sd[n]))
                for n in self._param_names]
        if all(s is nosh for s in p_sh + b_sh) and all(
                s is nosh for st in o_sh for s in jax.tree.leaves(st)):
            return step_fn          # single-device state: nothing to pin

        def cst(a, s):
            return a if s is nosh else jax.lax.with_sharding_constraint(a, s)

        def constrained(pa, ba, os_, lr, key, ss, *batch):
            np_, nb, nos, loss, nss, aux = step_fn(pa, ba, os_, lr, key,
                                                   ss, *batch)
            np_ = [cst(a, s) for a, s in zip(np_, p_sh)]
            nb = [cst(a, s) for a, s in zip(nb, b_sh)]
            nos = [jax.tree.map(cst, st, s) for st, s in zip(nos, o_sh)]
            return np_, nb, nos, loss, nss, aux

        return constrained

    def _run_auto(self, *args, _fn_factory=None, _key_tag=()):
        """AUTO-layout execution: jit with compiler-CHOSEN layouts for the
        params/buffers/opt-state args only (batch/lr/rng keep the default
        layout — relaying a fresh host batch out every step cost ResNet
        ~5%), compile per arg signature, query the chosen input formats,
        and device_put any mismatched state leaf ONCE — donated aliasing
        keeps every later step zero-copy. `_fn_factory`/`_key_tag` let
        many() run its scanned K-step program through the same treatment
        (args keep the (params, buffers, opt_states, ...) leading trio)."""
        auto_spec, fmt_attr, leaf_attr = _layout_api()

        flat, treedef = jax.tree.flatten(args)
        # only the batch part of the signature can vary between calls
        # (state shapes are fixed per TrainStep); keying on it alone keeps
        # the per-step key O(batch) instead of O(params)
        bflat, btree = jax.tree.flatten(args[6:])
        key = (_key_tag, len(flat), btree,
               tuple((a.shape, a.dtype) for a in bflat))
        ent = self._compiled_cache.get(key)
        if ent is None:
            auto = auto_spec
            specs = (auto, auto, auto) + (None,) * (len(args) - 3)
            # buffers (arg 1) are donated here too: their exit layouts
            # must alias their AUTO entry layouts for the trusted-skip
            # below to hold for >=2-D buffers
            jitted = jax.jit((_fn_factory or self._make_step_fn)(),
                             donate_argnums=(0, 1, 2) if self.donate else (),
                             in_shardings=specs,
                             out_shardings=auto_spec)
            # AUTO-layout lowering requires abstract avals (concrete
            # arrays carry layouts that would contradict AUTO)
            sds = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                               jnp.asarray(a).dtype), args)
            compiled = jitted.lower(*sds).compile()
            fmt_flat, fmt_tree = jax.tree.flatten(
                getattr(compiled, fmt_attr)[0])
            if fmt_tree != treedef:  # defensive: structures must agree
                raise RuntimeError("input_formats structure mismatch")
            # leaves of args 0/1/2 (params, buffers, opt states) are
            # rebound from the step's outputs, so their relayout may
            # DONATE the source buffer (no transient double copy of the
            # model+optimizer); lr/rng/batch buffers are caller-owned
            own = set()
            off = 0
            for i, a in enumerate(args):
                n = len(jax.tree.flatten(a)[0])
                if i in (0, 1, 2):
                    own.update(range(off, off + n))
                off += n
            ent = self._compiled_cache[key] = (compiled, fmt_flat, own)
        compiled, fmt_flat, own = ent
        # after the first successful call under THIS entry the own (state)
        # leaves come back from the step's outputs already in the chosen
        # layouts (donated aliasing) — checking ~2k Formats per step cost
        # ~15 ms of Python on the 860M-param UNet, so trust the aliasing
        # and only verify the few caller-owned leaves (batch/lr/rng). The
        # trust is keyed to ONE entry at a time: switching batch shapes
        # relayouts the state into the new entry's formats, so any other
        # entry must re-verify from scratch.
        trusted = self._layout_owner == key
        moved = [a if (trusted and i in own)
                 or getattr(a, leaf_attr, None) == f
                 else jax.device_put(a, f, donate=(i in own))
                 for i, (a, f) in enumerate(zip(flat, fmt_flat))]
        try:
            out = compiled(*jax.tree.unflatten(treedef, moved))
        except ValueError as e:
            # ONLY argument-layout mismatches are retryable (raised at
            # arg-processing time, BEFORE execution/donation — a state
            # leaf was rebound externally, e.g. load_state_dict
            # mid-training). Genuine runtime failures (OOM, asserts) may
            # have consumed donated buffers; retrying would bury the real
            # error under "Array has been deleted".
            if trusted and "layout" in str(e).lower():
                self._layout_owner = None
                return self._run_auto(*args, _fn_factory=_fn_factory,
                                      _key_tag=_key_tag)
            raise
        self._layout_owner = key
        return out

    def _make_step_fn(self):
        """Construct the pure step function (params/buffers/opt-state pytrees
        in, updated pytrees out) — subclasses jit it with their own shardings."""
        model = self.model
        opt = self.optimizer
        sd = model.state_dict()
        params = {n: t for n, t in sd.items() if isinstance(t, Tensor) and not t.stop_gradient}
        buffers = {n: t for n, t in sd.items() if n not in params}
        self._param_names = list(params.keys())
        self._buffer_names = list(buffers.keys())
        name_by_id = {id(p): n for n, p in params.items()}
        loss_fn = self.loss_fn
        has_aux = self.has_aux

        scaler = self.scaler

        def step_fn(param_arrays, buffer_arrays, opt_states, lr, rng_key,
                    scaler_state, *batch):
            arrays = dict(zip(self._param_names, param_arrays))
            arrays.update(zip(self._buffer_names, buffer_arrays))
            with random_state.fork_rng(rng_key):
                with model.use_state(arrays):
                    sd_live = model.state_dict()
                    live_params = [sd_live[n] for n in self._param_names]
                    for p in live_params:
                        p.grad = None
                    res = loss_fn(model, *[Tensor(b) for b in batch])
                    if has_aux:
                        loss, aux = res
                        aux_arrays = jax.tree.map(
                            lambda t: t._data if isinstance(t, Tensor) else t,
                            aux)
                    else:
                        loss, aux_arrays = res, ()
                    found_inf = jnp.zeros((), jnp.bool_)
                    if scaler is None:
                        loss.backward()
                    else:
                        # dynamic loss scaling, fully in-program (the
                        # reference's GradScaler.scale/unscale_/update,
                        # grad_scaler.py (U), staged into one XLA step)
                        scale, good, bad = scaler_state
                        (loss * Tensor(scale)).backward()
                        inv = 1.0 / scale
                        with _tape.no_grad():
                            for p in live_params:
                                if p.grad is None:
                                    continue
                                g32 = p.grad._data.astype(jnp.float32) * inv
                                found_inf = found_inf | ~jnp.all(jnp.isfinite(g32))
                                p.grad._data = g32.astype(p.grad._data.dtype)
                    params_grads = [(p, p.grad) for p in live_params if p.grad is not None]
                    if opt._grad_clip is not None:
                        params_grads = opt._grad_clip(params_grads)
                    if self.update_barrier and params_grads:
                        # keep the dW matmuls OUT of the optimizer-update
                        # fusions: fused (dW + AdamW) ops ran at ~18
                        # TFLOP/s on the r4 BERT trace vs ~60+ for the
                        # bare matmul — the epilogue's 4 full-size f32
                        # outputs wreck the MXU pipeline
                        barr = jax.lax.optimization_barrier(
                            [g._data for _, g in params_grads])
                        for (_, g), na in zip(params_grads, barr):
                            g._data = na
                    grad_by_id = {id(p): g for p, g in params_grads}
                    new_params = []
                    new_opt_states = []
                    with _tape.no_grad():
                        for n, st in zip(self._param_names, opt_states):
                            p = sd_live[n]
                            g = grad_by_id.get(id(p))
                            if g is None:
                                new_params.append(p._data)
                                new_opt_states.append(st)
                                continue
                            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
                            g_arr = opt._regularized_grad(p, g._data)
                            np_, nst = opt._update_for(p, p._data, g_arr, st,
                                                       plr)
                            if scaler is not None:
                                # skip the step on inf/nan grads
                                np_ = jnp.where(found_inf, p._data, np_)
                                nst = jax.tree.map(
                                    lambda new, old: jnp.where(found_inf, old, new),
                                    nst, st)
                            new_params.append(np_)
                            new_opt_states.append(nst)
                    new_buffers = [model.state_dict()[n]._data for n in self._buffer_names]
                    # clear tracer grads so they don't leak out of the trace
                    for p in live_params:
                        p.grad = None
            if scaler is None:
                new_scaler_state = scaler_state
            else:
                # GradScaler.update() semantics, traced
                bad1 = jnp.where(found_inf, bad + 1, jnp.zeros_like(bad))
                good1 = jnp.where(found_inf, jnp.zeros_like(good), good + 1)
                dec = found_inf & (bad1 >= scaler._decr_every)
                inc = (~found_inf) & (good1 >= scaler._incr_every)
                if not scaler._dynamic:
                    dec = inc = jnp.zeros((), jnp.bool_)
                new_scale = jnp.where(
                    dec, jnp.maximum(scale * scaler._decr_ratio, 1.0),
                    jnp.where(inc, scale * scaler._incr_ratio, scale))
                new_scaler_state = (new_scale,
                                    jnp.where(inc, jnp.zeros_like(good1), good1),
                                    jnp.where(dec, jnp.zeros_like(bad1), bad1))
            return (new_params, new_buffers, new_opt_states, loss._data,
                    new_scaler_state, aux_arrays)

        return step_fn

    def _marshal(self, *batch, draw_key=True):
        """Build the exact positional argument tuple __call__ feeds the
        jitted step (also used by cost_analysis, which must NOT advance the
        global RNG stream — pass draw_key=False there)."""
        if self._jitted is None:
            self._ensure_states()
            self._build()
        # the state Tensor OBJECTS are stable across steps (__call__
        # rebinds their ._data in place) — walking the module tree per
        # step cost ~10 ms of Python on an 860M-param model
        sd = getattr(self, "_sd_cache", None)
        if sd is None:
            sd = self._sd_cache = self.model.state_dict()
        param_arrays = [sd[n]._data for n in self._param_names]
        buffer_arrays = [sd[n]._data for n in self._buffer_names]
        opt = self.optimizer
        opt_states = [opt._state_for(sd[n]) for n in self._param_names]
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        rng_key = (random_state.next_key() if draw_key
                   else jax.random.PRNGKey(0))
        batch_arrays = [b._data if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        if self.scaler is not None:
            scaler_state = (jnp.asarray(self.scaler._scale, jnp.float32),
                            jnp.asarray(self.scaler._good_steps, jnp.int32),
                            jnp.asarray(self.scaler._bad_steps, jnp.int32))
        else:
            scaler_state = ()
        return (sd, param_arrays, buffer_arrays, opt_states, lr, rng_key,
                scaler_state, batch_arrays)

    def cost_analysis(self, *batch):
        """XLA cost analysis of the COMPILED step executable (flops, bytes
        accessed, ...) — post-optimization counts, so CSE'd/DCE'd work is
        not credited to utilization numbers. Compiling here re-runs XLA
        (the executable cache may or may not absorb it) — acceptable for
        benchmarking, not for hot paths; the pre-optimization
        lowering-level analysis is only the fallback."""
        (_, param_arrays, buffer_arrays, opt_states, lr, rng_key,
         scaler_state, batch_arrays) = self._marshal(*batch, draw_key=False)
        lowered = self._jitted.lower(param_arrays, buffer_arrays, opt_states,
                                     lr, rng_key, scaler_state, *batch_arrays)
        try:
            cost = lowered.compile().cost_analysis()
        except Exception:
            cost = None
        if not cost:
            cost = lowered.cost_analysis()
        # jax returns either a dict or a per-device list of dicts
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return cost

    def many(self, batches):
        """Run K optimizer steps as ONE compiled program (`lax.scan` over
        the single-step fn): the same UPDATE math as K sequential
        __call__s (bitwise for RNG-free steps; see the RNG caveat below) —
        K parameter/optimizer updates, each with its own RNG key — but one
        host dispatch, which matters when dispatch latency (not compute)
        bounds wall-clock (the r4 ResNet trace: device-side 2,269 img/s vs
        ~1,700 measured through the tunnel). `batches` is a list of K
        equal-shape batch tuples. LR is read ONCE for the whole pack (an
        LRScheduler stepped between many() calls behaves like a
        per-K-steps schedule), and the K keys come from ONE split of the
        global stream — statistically equivalent to, but not bitwise the
        same as, the K successive draws sequential __call__s make
        (dropout masks differ; RNG-free steps match exactly).
        Returns the K per-step losses as one Tensor [K]."""
        if not batches:
            raise ValueError("many() expects at least one batch")
        t0 = time.perf_counter()
        if self.has_aux:
            raise ValueError("many() does not support has_aux steps (the "
                             "per-step aux would be K-stacked; run "
                             "__call__ per step instead)")
        cls = type(self)
        if (cls._build is not TrainStep._build
                or cls._make_step_fn is not TrainStep._make_step_fn
                or cls._run_auto is not TrainStep._run_auto):
            # a subclass that overrides dispatch (GroupShardedTrainStep's
            # sharded _build/_place_states) would be silently bypassed by
            # this scan — params would compile UNSHARDED; benign
            # subclasses that keep the dispatch methods inherit many()
            raise NotImplementedError(
                f"many() supports TrainStep's own dispatch; "
                f"{cls.__name__} overrides it and must run one step per "
                "call")
        k = len(batches)
        # marshal STATE only (no batch: its arrays would be converted
        # here and discarded, a wasted H2D copy on the latency path)
        (sd, param_arrays, buffer_arrays, opt_states, lr, _, scaler_state,
         _) = self._marshal(draw_key=False)
        tuples = [b if isinstance(b, (tuple, list)) else (b,)
                  for b in batches]
        stacked = [
            jnp.stack([(b[i]._data if isinstance(b[i], Tensor)
                        else jnp.asarray(b[i])) for b in tuples])
            for i in range(len(tuples[0]))
        ]
        rng_keys = jax.random.split(random_state.next_key(), k)

        def make_many_fn():
            step_fn = self._constrain_state_outputs(self._make_step_fn())

            def many_fn(pa, ba, os_, lr_, keys, ss, *stk):
                def body(carry, xs):
                    pa_, ba_, os2, ss2 = carry
                    key = xs[0]
                    batch = xs[1:]
                    np_, nb, nos, loss, nss, _aux = step_fn(
                        list(pa_), list(ba_), list(os2), lr_, key, ss2,
                        *batch)
                    return (tuple(np_), tuple(nb), tuple(nos), nss), loss

                (pa2, ba2, os2, ss2), losses = jax.lax.scan(
                    body, (tuple(pa), tuple(ba), tuple(os_), ss),
                    (keys,) + stk)
                return list(pa2), list(ba2), list(os2), losses, ss2

            return many_fn

        run_args = (param_arrays, buffer_arrays, opt_states, lr, rng_keys,
                    scaler_state) + tuple(stacked)
        if self.auto_layout:
            # big-parameter models (SD-UNet) NEED the AUTO-layout
            # treatment inside the scan too — plain jit re-pins the
            # donated entry layouts and re-introduces the per-step
            # master-weight layout flips the r4 trace diagnosed
            (new_params, new_buffers, new_opt_states, losses,
             new_scaler_state) = self._run_auto(
                *run_args, _fn_factory=make_many_fn, _key_tag=("many", k))
        else:
            ckey = ("many", k,
                    tuple((a.shape, str(a.dtype)) for a in stacked))
            jitted = self._compiled_cache.get(ckey)
            if jitted is None:
                jitted = jax.jit(
                    make_many_fn(),
                    donate_argnums=(0, 1, 2) if self.donate else ())
                self._compiled_cache[ckey] = jitted
            (new_params, new_buffers, new_opt_states, losses,
             new_scaler_state) = jitted(*run_args)
        if self.scaler is not None:
            (self.scaler._scale, self.scaler._good_steps,
             self.scaler._bad_steps) = new_scaler_state
        opt = self.optimizer
        for n, arr in zip(self._param_names, new_params):
            sd[n]._data = arr
        for n, arr in zip(self._buffer_names, new_buffers):
            sd[n]._data = arr
        for n, st in zip(self._param_names, new_opt_states):
            opt._accumulators[id(sd[n])] = st
        opt._step_count += k
        dt = time.perf_counter() - t0
        _STEP_COUNT.inc(k)
        # one observation per pack: the per-step average of the scanned
        # K-step program (individual in-scan steps are not host-visible)
        _STEP_SECONDS.observe(dt / k)
        return Tensor(losses)

    def __call__(self, *batch):
        t0 = time.perf_counter()
        (sd, param_arrays, buffer_arrays, opt_states, lr, rng_key,
         scaler_state, batch_arrays) = self._marshal(*batch)
        opt = self.optimizer
        run = self._run_auto if self.auto_layout else self._jitted
        (new_params, new_buffers, new_opt_states, loss, new_scaler_state,
         aux_arrays) = run(
            param_arrays, buffer_arrays, opt_states, lr, rng_key, scaler_state,
            *batch_arrays
        )
        if self.scaler is not None:
            self.scaler._scale, self.scaler._good_steps, self.scaler._bad_steps = (
                new_scaler_state)
        for n, arr in zip(self._param_names, new_params):
            sd[n]._data = arr
        for n, arr in zip(self._buffer_names, new_buffers):
            sd[n]._data = arr
        for n, st in zip(self._param_names, new_opt_states):
            opt._accumulators[id(sd[n])] = st
        opt._step_count += 1
        dt = time.perf_counter() - t0
        _STEP_COUNT.inc()
        _STEP_SECONDS.observe(dt)
        if batch_arrays and hasattr(batch_arrays[0], "shape") \
                and batch_arrays[0].shape and dt > 0:
            _STEP_IPS.observe(batch_arrays[0].shape[0] / dt)
        if self.has_aux:
            return Tensor(loss), jax.tree.map(Tensor, aux_arrays)
        return Tensor(loss)
