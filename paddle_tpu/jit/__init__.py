from .api import (
    to_static, not_to_static, save, load, InputSpec, StaticFunction,
    TranslatedLayer, enable_to_static, ignore_module,
)
from .train_step import TrainStep
