"""dy2static-lite: AST conversion of Python `if`/`while` over traced
tensors (ref: python/paddle/jit/dy2static/ (U), SURVEY.md §2.2 P8 — the
reference rewrites dygraph Python control flow into ConditionalBlock /
While ops so `to_static` can compile data-dependent branches).

TPU-native stance: `to_static` is jax tracing, so control flow over
CONCRETE Python values needs no conversion at all (the trace simply
unrolls/specializes, and re-traces per input signature). What tracing
cannot do is a branch or loop whose predicate is a traced tensor — that is
exactly what `static.nn.cond` / `static.nn.while_loop` (lax select +
lax.while_loop) stage. This module closes the gap the reference closes
with its AST transformer, scoped the same way:

- every `if`/`while` statement is rewritten into a call to a runtime
  dispatch helper (`convert_ifelse` / `convert_while`);
- at RUN time the helper inspects the predicate: a plain Python/concrete
  value keeps exact Python semantics (one branch runs, loops run
  eagerly/unroll under trace); a traced or symbolic tensor stages;
- variables assigned in a branch/loop body become explicit carries —
  rebound from a tuple on entry, returned on exit — so the rewrite never
  needs `nonlocal` and AugAssign keeps working;
- names possibly unbound before the statement are carried as an `UNDEF`
  sentinel: a temp defined inside the branch/loop body works, a genuine
  read-before-assignment raises a NameError naming the variable.

Early exits (r5, VERDICT r4 item 1): `return`/`break`/`continue` inside
convertible control flow rewrite into flag-guarded dataflow BEFORE the
statement conversion (`_EarlyExit`): per-loop break/continue flags and a
function-level (ret, site) pair become ordinary staged carries, every
statement after a may-exit point is guarded so locals freeze at the exit,
loops gain `not flag` predicate conjuncts (a for-range with exits becomes
an equivalent while), and a site-dispatch chain at the function end
re-evaluates the chosen return expression ONCE from the frozen locals —
no return-value carries (the reference carries magic-number placeholders
instead). A greedy decode with a data-dependent early exit stages as one
program. `for x in <traced tensor>` stages as one differentiable
lax.scan (`convert_for_iter`); other iterables keep exact Python
semantics. Loop temps first assigned inside a staged while are
shape-probed (jax.eval_shape) and zeros-initialized so the post-loop
read works; after a ZERO-trip staged loop such a temp reads as zeros
rather than raising — the documented staging trade-off.

Deliberately NOT converted (the statement stays plain Python, which keeps
working for concrete predicates and raises jax's concretization error for
traced ones): `del`/`global`/`nonlocal` in bodies; `while/else` /
`for/else`; exits inside `with`/`try` or non-range `for` loops;
generators/coroutines; impure return expressions evaluate at the
function-end dispatch rather than the return site; functions whose
source is unavailable. Conversion IS transitive through calls (r5):
user call sites inside a converted function route through `convert_call`,
so undecorated helpers stage too (framework/builtin callables pass
through untouched; mark a function with `paddle.jit.not_to_static` to
opt out).
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

__all__ = ["convert_to_static", "convert_call", "convert_ifelse",
           "convert_while", "convert_for_range", "convert_for_iter",
           "convert_logical_and", "convert_logical_or",
           "convert_logical_not", "range_parts", "UndefinedVar", "UNDEF",
           "UnconvertibleControlFlowError", "unconvertible_guard"]


class UndefinedVar:
    """Sentinel carried for names not yet bound when a converted statement
    runs. Any actual USE raises — matching the NameError the untransformed
    code would have raised, just later and with context."""

    __slots__ = ("name",)

    def __init__(self, name="<var>"):
        self.name = name

    def _boom(self):
        raise NameError(
            f"variable {self.name!r} is read on a path through converted "
            "control flow where it was never assigned (dy2static carries "
            "it as undefined); assign it before the if/while")

    def __getattr__(self, item):
        self._boom()

    def __call__(self, *a, **k):
        self._boom()

    def __bool__(self):
        self._boom()

    def __iter__(self):
        self._boom()

    def __repr__(self):
        return f"UndefinedVar({self.name!r})"


# operator dunders are looked up on the TYPE (bypassing __getattr__), so a
# sentinel used in arithmetic/indexing/comparison must trip explicitly
def _undef_op(name):
    def op(self, *a, **k):
        self._boom()
    op.__name__ = name
    return op


for _dunder in ("__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
                "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
                "__rfloordiv__", "__mod__", "__rmod__", "__pow__",
                "__rpow__", "__matmul__", "__rmatmul__", "__neg__",
                "__pos__", "__abs__", "__lt__", "__le__", "__gt__",
                "__ge__", "__getitem__", "__setitem__", "__len__",
                "__float__", "__int__", "__index__", "__contains__"):
    setattr(UndefinedVar, _dunder, _undef_op(_dunder))


UNDEF = UndefinedVar()


def _is_traced(x):
    """True when `x` cannot be bool()-ed: a jax tracer, or a Tensor whose
    value is a tracer / a static-graph symbol."""
    import jax

    from ..core.tensor import Tensor

    if isinstance(x, Tensor):
        x = x._data
    if isinstance(x, jax.core.Tracer):
        return True
    return type(x).__name__ in ("_SymArr", "_GradSym")


class UnconvertibleControlFlowError(TypeError):
    """A traced predicate reached an if/while the converter deliberately
    left as plain Python. The message cites the analysis rule code(s) and
    hint(s) — the same diagnostics `paddle_tpu.analysis.check` reports
    before tracing (the ErrorData-style shared report)."""


def unconvertible_guard(pred, reasons, filename, line):
    """Runtime guard the transformer wraps around the test of an
    UNCONVERTIBLE if/while: concrete predicates pass through with exact
    Python semantics; a traced predicate raises a source-mapped error
    citing each PTA diagnostic instead of jax's deep concretization
    traceback. `reasons`: ((code, absolute_line), ...)."""
    if not _is_traced(pred):
        return pred
    from ..analysis.diagnostics import make

    parts = [make(code, filename, ln).format() for code, ln in reasons]
    raise UnconvertibleControlFlowError(
        f"{filename}:{line}: this if/while has a traced (tensor) "
        "predicate, but the statement contains construct(s) dy2static "
        "deliberately does not stage — run "
        "paddle_tpu.analysis.check(fn) before tracing to see these "
        "findings early:\n" + "\n".join(parts))


def _to_carry(x, name):
    """A loop carry entering the staged path must be an array value."""
    from ..core.tensor import Tensor
    from ..tensor.creation import to_tensor

    if isinstance(x, Tensor):
        return x
    if isinstance(x, (bool, int, float, complex)) or hasattr(x, "dtype"):
        return to_tensor(x)
    raise TypeError(
        f"variable {name!r} of type {type(x).__name__} cannot be carried "
        "through staged control flow (only tensors and numbers can); hoist "
        "it out of the if/while or keep the predicate concrete")


def convert_ifelse(pred, true_fn, false_fn, vals, names, guard=False):
    """Runtime dispatch for a converted `if`: concrete predicate keeps
    exact Python semantics (one branch runs); traced predicate builds both
    branches and stages a select per assigned variable. `guard=True` marks
    the flag-guard ifs the early-exit rewrite generates: a name assigned
    on one path only merges as select(pred, value, zeros) instead of the
    loud UndefinedVar — safe because the rewrite only reads such names on
    paths where the guard ran (locals freeze at the exit)."""
    from ..core.tensor import Tensor

    if isinstance(pred, UndefinedVar):
        pred._boom()
    if not _is_traced(pred):
        if isinstance(pred, Tensor):
            pred = bool(pred)
        return true_fn(vals) if pred else false_fn(vals)

    from ..static.nn import cond as static_cond

    if not names:
        # a branch that binds no names can only act through side effects
        # (list.append, dict/attr mutation) — under a traced predicate
        # BOTH branches would execute unconditionally, so wrong results
        # would silently replace the loud pre-conversion error
        raise TypeError(
            "a converted `if` over a traced tensor predicate assigns no "
            "variables — its body works only by side effects, which "
            "cannot be staged (both branches trace). Assign the result "
            "to a variable, or call paddle.static.nn.cond directly.")
    # tracing: both branches run (the reference records both
    # ConditionalBlocks too); outputs merge by a staged select
    t_out = true_fn(vals)
    f_out = false_fn(vals)
    sel_idx, t_sel, f_sel = [], [], []
    merged = [None] * len(names)
    for i, (tv, fv, name) in enumerate(zip(t_out, f_out, names)):
        t_undef = isinstance(tv, UndefinedVar)
        f_undef = isinstance(fv, UndefinedVar)
        if t_undef and f_undef:
            merged[i] = UndefinedVar(name)      # stays undefined, loudly
        elif t_undef or f_undef:
            if guard:
                # early-exit guard: the rewrite reads this name only on
                # paths where the assigning branch ran — the other side
                # selects zeros that are never observed
                import jax.numpy as jnp

                dv = _to_carry(fv if t_undef else tv, name)
                zero = Tensor(jnp.zeros_like(dv._data))
                sel_idx.append(i)
                t_sel.append(zero if t_undef else dv)
                f_sel.append(dv if t_undef else zero)
            else:
                # defined on one path only: usable downstream on neither
                # (staged code runs once) — bind the loud sentinel
                merged[i] = UndefinedVar(name)
        elif tv is fv:
            merged[i] = tv                      # untouched by both
        else:
            if (tv is None) != (fv is None):
                if name.startswith(_RV):
                    raise TypeError(
                        "a staged early-exit function must return a "
                        "value of the same structure on EVERY path: an "
                        "implicit `return None` fall-through (or a bare "
                        "`return`) cannot merge with tensor returns "
                        "under a traced predicate — add an explicit "
                        "final return")
                raise TypeError(
                    f"variable {name!r} is None on one branch of a "
                    "staged `if` — both paths must assign an array "
                    "value (staged selects cannot mix None with "
                    "tensors)")
            sel_idx.append(i)
            t_sel.append(tv)
            f_sel.append(fv)
    if sel_idx:
        # the branch lambdas return tuples, so cond rebuilds a tuple of
        # the same arity (including arity 1)
        picked = static_cond(pred, lambda: tuple(t_sel),
                             lambda: tuple(f_sel))
        for i, v in zip(sel_idx, picked):
            merged[i] = v
    return tuple(merged)


def _probe_body_carries(run_body, vals, names, keep):
    """Discover shapes of names undefined BEFORE a staged loop but
    assigned by its body (`t = step(x)` inside a decode `while`):
    jax.eval_shape the Tensor-level body once — no compute, no tape, RNG
    stream restored — and zeros-init those carries, so the value is
    readable after the loop (the early-exit dispatch reads it under its
    guard flag). A body that READS an undefined name before assigning it
    raises inside the probe -> {} (the loud NameError then surfaces at
    the real trace, naming the variable). After a ZERO-trip staged loop
    such a carry reads as zeros rather than raising — documented
    trade-off of staging. run_body(vals_tuple) -> vals_tuple."""
    import jax
    import jax.numpy as jnp

    from ..core import random as _rng
    from ..core import tape as _tape
    from ..core.tensor import Tensor
    from ..tensor.creation import to_tensor

    maybe = [i for i in range(len(vals)) if i not in keep]
    if not maybe:
        return {}
    found_box = {}

    def arr_fn(*arrs):
        vs = list(vals)
        for j, i in enumerate(keep):
            vs[i] = Tensor(arrs[j])
        for i in maybe:
            vs[i] = UndefinedVar(names[i])
        with _tape.no_grad():
            res = run_body(tuple(vs))
        outs, idxs = [], []
        for i in maybe:
            v = res[i]
            if isinstance(v, Tensor):
                idxs.append(i)
                outs.append(v._data)
        found_box["idx"] = idxs
        return tuple(outs)

    snap = _rng.get_rng_state()
    try:
        ins = [_to_carry(vals[i], names[i])._data for i in keep]
        shapes = jax.eval_shape(arr_fn, *ins)
    except Exception:
        return {}
    finally:
        _rng.set_rng_state(snap)
    return {i: to_tensor(jnp.zeros(s.shape, s.dtype))
            for i, s in zip(found_box.get("idx", ()), shapes)}


def convert_while(cond_fn, body_fn, vals, names):
    """Runtime dispatch for a converted `while`: a concrete first
    predicate runs the plain Python loop (which unrolls under trace — jax
    semantics for concrete trip counts); a traced predicate stages ONE
    lax.while_loop over the defined carries. A predicate that BECOMES
    traced mid-loop (a staged break/return flag flipping a concrete
    bound, `while i < 100: ... if done(x): break`) continues as one
    staged while from the current state — already-run iterations stay
    unrolled. Names unbound before the loop carry per
    _probe_body_carries; a genuine read-before-assign raises a NameError
    naming the variable."""
    first = cond_fn(vals)
    if isinstance(first, UndefinedVar):
        first._boom()
    if not _is_traced(first):
        from ..core.tensor import Tensor

        def as_bool(p):
            return bool(p) if isinstance(p, Tensor) else p

        p = as_bool(first)
        while p:
            vals = body_fn(vals)
            nxt = cond_fn(vals)
            if _is_traced(nxt):
                # data-dependent from here on: stage the remainder
                return _convert_while_staged(cond_fn, body_fn, vals, names)
            p = as_bool(nxt)
        return vals
    return _convert_while_staged(cond_fn, body_fn, vals, names)


def _convert_while_staged(cond_fn, body_fn, vals, names):
    from ..static.nn import while_loop as static_while

    keep = [i for i, v in enumerate(vals)
            if not isinstance(v, UndefinedVar)]
    # names first assigned INSIDE the body (decode temps) become carries
    # with a probed zeros init — see _probe_body_carries
    extra = _probe_body_carries(body_fn, vals, names, keep)
    keep = sorted(set(keep) | set(extra))
    if not keep:
        raise TypeError(
            "a converted `while` over a traced tensor predicate carries "
            "no defined variables — initialize the loop state before the "
            "loop (lax.while_loop needs loop-carried values), or call "
            "paddle.static.nn.while_loop directly.")
    carried = [extra[i] if i in extra else _to_carry(vals[i], names[i])
               for i in keep]

    def full(vs):
        out = list(vals)
        for i, v in zip(keep, vs):
            out[i] = v
        for i in range(len(out)):
            if isinstance(out[i], UndefinedVar):
                out[i] = UndefinedVar(names[i])
        return tuple(out)

    def body_w(*vs):
        res = body_fn(full(vs))
        out = []
        for i in keep:
            v = res[i]
            if isinstance(v, UndefinedVar):
                v._boom()
            out.append(v)
        return out

    outs = static_while(lambda *vs: cond_fn(full(vs)), body_w, carried)
    if len(carried) == 1 and not isinstance(outs, (tuple, list)):
        outs = [outs]
    final = list(vals)
    for i, v in zip(keep, outs):
        final[i] = v
    for i in range(len(final)):
        if isinstance(final[i], UndefinedVar):
            final[i] = UndefinedVar(names[i])
    return tuple(final)


def convert_logical_and(lx, ly):
    """`a and b` inside a converted statement's predicate (ref
    convert_logical_and): Python short-circuit semantics for concrete
    values; traced values evaluate BOTH sides and stage logical_and
    (the reference's behavior — no short-circuit once staged)."""
    x = lx()
    if isinstance(x, UndefinedVar):
        x._boom()
    if not _is_traced(x):
        if not x:
            return x
        return ly()
    y = ly()
    from ..tensor.logic import logical_and

    return logical_and(_to_carry(x, "<and-lhs>").astype("bool"),
                       _to_carry(y, "<and-rhs>").astype("bool"))


def convert_logical_or(lx, ly):
    x = lx()
    if isinstance(x, UndefinedVar):
        x._boom()
    if not _is_traced(x):
        if x:
            return x
        return ly()
    y = ly()
    from ..tensor.logic import logical_or

    return logical_or(_to_carry(x, "<or-lhs>").astype("bool"),
                      _to_carry(y, "<or-rhs>").astype("bool"))


def convert_logical_not(x):
    if isinstance(x, UndefinedVar):
        x._boom()
    if not _is_traced(x):
        return not x
    from ..tensor.logic import logical_not

    return logical_not(_to_carry(x, "<not-operand>").astype("bool"))


def _range_normalize(args):
    """(start, stop, step) from range-call args, with Python's zero-step
    check (shared by convert_for_range and range_parts)."""
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        (start, stop), step = args, 1
    else:
        start, stop, step = args

    from ..core.tensor import Tensor

    if isinstance(step, (int, Tensor)) and not _is_traced(step) \
            and int(step) == 0:
        raise ValueError("range() arg 3 must not be zero")
    return start, stop, step


def _range_count_arrays(start_a, stop_a, step_a):
    """Sign-aware integer ceil-div trip count on arrays — a float32
    round-trip loses exactness at |bounds| >= 2^24 (one lost iteration
    at 16777217)."""
    import jax.numpy as jnp

    n_pos = (stop_a - start_a + step_a - 1) // step_a
    n_neg = (start_a - stop_a - step_a - 1) // (-step_a)
    return jnp.maximum(
        0, jnp.where(step_a > 0, n_pos, n_neg)).astype(jnp.int32)


def convert_for_range(range_args, body_fn, vals, names,
                      target_name="<target>", target_prior=UNDEF):
    """Runtime dispatch for a converted `for <target> in range(...)`:
    concrete bounds run the plain Python loop (unrolls under trace); a
    traced bound stages ONE lax while_loop with the trip count computed
    on-device. body_fn((i, vals)) -> vals. Returns (final_i, vals) —
    after an EMPTY range the target keeps its prior binding
    (`target_prior`, Python semantics; after a staged empty range that
    only works when the prior value is a tensor/number — otherwise the
    target pins to `start`). Carries follow convert_while's rules
    (undefined names drop out of the carry; cross-iteration reads raise
    by name)."""
    from ..core.tensor import Tensor

    start, stop, step = _range_normalize(range_args)

    if not any(_is_traced(v) for v in (start, stop, step)):
        as_py = [int(v) if isinstance(v, Tensor) else v
                 for v in (start, stop, step)]
        # empty range keeps the prior binding; an unbound prior stays the
        # loud sentinel, renamed so the eventual NameError names the var
        i = (UndefinedVar(target_name)
             if isinstance(target_prior, UndefinedVar) else target_prior)
        for i in range(*as_py):
            vals = body_fn((i, vals))
        return i, vals

    import jax.numpy as jnp

    from ..static.nn import while_loop as static_while
    from ..tensor.creation import to_tensor

    def arr(v):
        return v._data if isinstance(v, Tensor) else jnp.asarray(v)

    start_a, stop_a, step_a = arr(start), arr(stop), arr(step)
    n_iters = _range_count_arrays(start_a, stop_a, step_a)

    keep = [i for i, v in enumerate(vals)
            if not isinstance(v, UndefinedVar)]
    extra = _probe_body_carries(
        lambda vs: body_fn((to_tensor(start_a), vs)), vals, names, keep)
    keep = sorted(set(keep) | set(extra))

    def full(vs):
        out = list(vals)
        for i, v in zip(keep, vs):
            out[i] = v
        for i in range(len(out)):
            if isinstance(out[i], UndefinedVar):
                out[i] = UndefinedVar(names[i])
        return tuple(out)

    def cond_w(k, i, *vs):
        from ..core.tensor import Tensor

        kd = k._data if isinstance(k, Tensor) else k
        return Tensor(kd < n_iters)

    def body_w(k, i, *vs):
        res = body_fn((i, full(vs)))
        out = []
        for j in keep:
            v = res[j]
            if isinstance(v, UndefinedVar):
                v._boom()
            out.append(v)
        return [k + 1, i + to_tensor(step_a)] + out

    carried = [extra[i] if i in extra else _to_carry(vals[i], names[i])
               for i in keep]
    outs = static_while(cond_w, body_w,
                        [to_tensor(jnp.zeros((), jnp.int32)),
                         to_tensor(start_a)] + carried)
    final_i = outs[1] - to_tensor(step_a)  # last iterated value...
    # ...except for an empty range, where Python keeps the target's prior
    # binding — honored when the prior is array-valued; otherwise the
    # staged code pins it to `start` deterministically
    from ..core.op_call import apply as _apply

    if isinstance(target_prior, (Tensor, int, float)) \
            and not isinstance(target_prior, bool):
        empty_val = arr(target_prior).astype(start_a.dtype)
    else:
        empty_val = start_a
    final_i = _apply(
        lambda n, fi, st: jnp.where(n > 0, fi, st),
        to_tensor(n_iters), final_i, to_tensor(empty_val),
        _op_name="for_range_final")
    final = list(vals)
    for i, v in zip(keep, outs[2:]):
        final[i] = v
    for i in range(len(final)):
        if isinstance(final[i], UndefinedVar):
            final[i] = UndefinedVar(names[i])
    return final_i, tuple(final)


def range_parts(*args):
    """(start, trip_count, step) for range(*args) — plain ints for
    concrete bounds (the rewritten while unrolls under trace exactly like
    the plain for did), scalar Tensors when any bound is traced (the
    while stages). Used by the early-exit rewrite's for->while form."""
    from ..core.tensor import Tensor

    start, stop, step = _range_normalize(args)
    if not any(_is_traced(v) for v in (start, stop, step)):
        as_py = [int(v) if isinstance(v, Tensor) else v
                 for v in (start, stop, step)]
        return as_py[0], len(range(*as_py)), as_py[2]

    import jax.numpy as jnp

    from ..tensor.creation import to_tensor

    def arr(v):
        return v._data if isinstance(v, Tensor) else jnp.asarray(v)

    start_a, stop_a, step_a = arr(start), arr(stop), arr(step)
    n = _range_count_arrays(start_a, stop_a, step_a)
    return to_tensor(start_a), to_tensor(n), to_tensor(step_a)


def convert_for_iter(seq, body_fn, vals, names,
                     target_name="<target>", target_prior=UNDEF):
    """Runtime dispatch for a converted `for <target> in <expr>` over a
    NON-range iterable (ref dy2static for-loop transform over Variable
    iterables): a traced Tensor sequence stages as ONE differentiable
    lax.scan over the leading axis (TPU-native: scan, not Python
    unrolling — and unlike while_loop, scan has a reverse-mode, so
    training loops over sequence tensors differentiate); every other
    iterable (lists, generators, concrete Tensors) runs the plain Python
    loop with exact semantics. body_fn((x, vals)) -> vals. Returns
    (final_target, vals)."""
    from ..core.tensor import Tensor

    if not _is_traced(seq):
        i = (UndefinedVar(target_name)
             if isinstance(target_prior, UndefinedVar) else target_prior)
        for i in seq:
            vals = body_fn((i, vals))
        return i, vals

    import jax
    import jax.numpy as jnp

    from ..core import tape as _tape
    from ..core.op_call import apply as _apply
    from ..tensor.creation import to_tensor

    seq_t = seq if isinstance(seq, Tensor) else to_tensor(seq)
    if seq_t.ndim < 1:
        raise TypeError(
            "cannot iterate a 0-d tensor in converted control flow")
    n = int(seq_t.shape[0])          # leading dim is static under trace

    keep = [i for i, v in enumerate(vals)
            if not isinstance(v, UndefinedVar)]
    row_probe = to_tensor(jnp.zeros(tuple(seq_t.shape[1:]),
                                    seq_t._data.dtype))
    extra = _probe_body_carries(
        lambda vs: body_fn((row_probe, vs)), vals, names, keep)
    keep = sorted(set(keep) | set(extra))
    if not keep:
        raise TypeError(
            "a converted `for` over a traced tensor sequence assigns no "
            "variables — its body works only by side effects, which "
            "cannot be staged (the scan body would run once at trace "
            "time, not once per row); assign results to variables, or "
            "keep the sequence concrete")
    carried = [extra[i] if i in extra else _to_carry(vals[i], names[i])
               for i in keep]

    def full(vs):
        out = list(vals)
        for i, v in zip(keep, vs):
            out[i] = v
        for i in range(len(out)):
            if isinstance(out[i], UndefinedVar):
                out[i] = UndefinedVar(names[i])
        return tuple(out)

    def scan_fn(seq_arr, *carry_arrs):
        def body(carry, row):
            with _tape.no_grad():
                res = body_fn((Tensor(row),
                               full([Tensor(a) for a in carry])))
            out = []
            for j, a in zip(keep, carry):
                v = res[j]
                if isinstance(v, UndefinedVar):
                    v._boom()
                va = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                if va.shape != a.shape or va.dtype != a.dtype:
                    raise TypeError(
                        f"staged for-loop body changed carried variable "
                        f"{names[j]!r} from {a.shape}/{a.dtype} to "
                        f"{va.shape}/{va.dtype} (loop-carried values must "
                        "keep shape and dtype)")
                out.append(va)
            return tuple(out), None

        final, _ = jax.lax.scan(body, tuple(carry_arrs), seq_arr)
        return final

    outs = _apply(scan_fn, seq_t, *carried, _op_name="for_iter_scan")
    if len(keep) == 1 and not isinstance(outs, (tuple, list)):
        outs = [outs]
    final = list(vals)
    for i, v in zip(keep, outs):
        final[i] = v
    for i in range(len(final)):
        if isinstance(final[i], UndefinedVar):
            final[i] = UndefinedVar(names[i])
    if n == 0:
        final_t = (UndefinedVar(target_name)
                   if isinstance(target_prior, UndefinedVar)
                   else target_prior)
    else:
        final_t = seq_t[n - 1]       # Python leaves target = last element
    return final_t, tuple(final)


# --------------------------------------------------------------------------
# AST transformation


_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _assigned_names(stmts):
    """Names bound by the statement list, in first-assignment order.
    Mutations through subscripts/attributes are not bindings; nested
    function/class bodies and comprehensions have their own scope."""
    out, seen = [], set()

    def add(name):
        if not name.startswith("__jst") and name not in seen:
            seen.add(name)
            out.append(name)

    def target_names(t):
        if isinstance(t, ast.Name):
            add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                target_names(e)
        elif isinstance(t, ast.Starred):
            target_names(t.value)

    def walk(body):
        for node in body:
            if isinstance(node, _SCOPES):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    add(node.name)
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    target_names(t)
            elif isinstance(node, ast.AugAssign):
                target_names(node.target)
            elif isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    target_names(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                target_names(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        target_names(item.optional_vars)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    add((a.asname or a.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    add(a.asname or a.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.NamedExpr) \
                        and isinstance(sub.target, ast.Name):
                    add(sub.target.id)
            for attr in ("body", "orelse", "finalbody"):
                child = getattr(node, attr, None)
                if child:
                    walk(child)
            for h in getattr(node, "handlers", ()) or ():
                walk(h.body)

    walk(list(stmts))
    return out


def _contains(stmts, kinds, skip_loops=False):
    """Any node of `kinds` in the statement list, not counting nested
    function/class scopes; with skip_loops, nested for/while bodies are
    skipped too (their break/continue belong to them)."""
    for node in stmts:
        if isinstance(node, _SCOPES):
            continue
        if isinstance(node, kinds):
            return True
        if skip_loops and isinstance(node, (ast.For, ast.AsyncFor,
                                            ast.While)):
            children = list(node.orelse)      # loop else runs after the loop
        else:
            children = []
            for a in ("body", "orelse", "finalbody"):
                children += getattr(node, a, None) or []
            for h in getattr(node, "handlers", ()) or ():
                children += h.body
        if children and _contains(children, kinds, skip_loops):
            return True
    return False


def _convertible(node):
    for body in (node.body, getattr(node, "orelse", [])):
        if not body:
            continue
        if _contains(body, (ast.Return, ast.Delete, ast.Global,
                            ast.Nonlocal)):
            return False
        if _contains(body, (ast.Break, ast.Continue), skip_loops=True):
            return False
    return True


_HELPER = "__jst"
_VALS = "__jst_vals"
# early-exit flag names deliberately do NOT start with "__jst":
# _assigned_names skips that prefix, and these flags must be CARRIED
# through staged control flow like ordinary variables
_RET = "_jst_ret"
_SITE = "_jst_site"
_RV = "_jst_rv"


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


def _one_arg():
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=_VALS)],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def _fn_def(name, body_stmts, carry_names, tail):
    """def <name>(__jst_vals): (a,b)=__jst_vals; <body>; <tail>"""
    body = []
    if carry_names:
        body.append(ast.Assign(targets=[_names_tuple(carry_names,
                                                     ast.Store)],
                               value=_load(_VALS)))
    body += body_stmts or [ast.Pass()]
    body.append(tail)
    return ast.FunctionDef(name=name, args=_one_arg(), body=body,
                           decorator_list=[], returns=None, type_params=[])


def _carries_return(names):
    return ast.Return(value=ast.Tuple(elts=[_load(n) for n in names],
                                      ctx=ast.Load()))


def _guarded_reads(names, prefix):
    """try: __jst_vN_i = a / except NameError: ... = __jst.UNDEF — reads
    the current value of each carry without tripping on unbound locals."""
    stmts = []
    undef = ast.Attribute(value=_load(_HELPER), attr="UNDEF",
                          ctx=ast.Load())
    for i, n in enumerate(names):
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[_store(f"{prefix}{i}")],
                             value=_load(n))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_load("NameError"),
                                     _load("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(targets=[_store(f"{prefix}{i}")],
                                 value=undef)])],
            orelse=[], finalbody=[]))
    return stmts


def _lam(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=expr)


def _helper_call(name, args):
    return ast.Call(
        func=ast.Attribute(value=_load(_HELPER), attr=name,
                           ctx=ast.Load()),
        args=args, keywords=[])


def _assign(name, value):
    return ast.Assign(targets=[_store(name)], value=value)


def _const(v):
    return ast.Constant(value=v)


def _not(expr):
    return ast.UnaryOp(op=ast.Not(), operand=expr)


def _terminates(stmts):
    """True when the statement list definitely returns on every path
    (last stmt is a return, an if/else whose branches both do, or a
    `while True:` with no break — the canonical `while True: ... if eos:
    return x` decode can only exit through a return)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return (bool(last.orelse) and _terminates(last.body)
                and _terminates(last.orelse))
    if isinstance(last, ast.While):
        test_true = (isinstance(last.test, ast.Constant)
                     and bool(last.test.value))
        return (test_true
                and not _contains(last.body, (ast.Break,),
                                  skip_loops=True))
    return False


class _LoopCtx:
    __slots__ = ("brk", "cont")

    def __init__(self, brk, cont):
        self.brk = brk          # flag name or None (no `break` targets it)
        self.cont = cont        # flag name or None


def _is_range_call(it):
    return (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range" and not it.keywords
            and 1 <= len(it.args) <= 3
            and not any(isinstance(a, ast.Starred) for a in it.args))


def _is_simple_range_for(node):
    return (isinstance(node, ast.For) and not node.orelse
            and isinstance(node.target, ast.Name)
            and _is_range_call(node.iter))


class _EarlyExit:
    """Function-level rewrite of `return`/`break`/`continue` into
    flag-guarded dataflow — the reference's return_transformer /
    break_continue_transformer (python/paddle/jit/dy2static/transformers/
    (U)), redesigned carry-free for TPU staging:

    - `return e` at site k becomes `_jst_ret = True; _jst_site = k`;
      every statement after a may-exit statement is wrapped in
      `if not <flags>:` so locals FREEZE at the exit moment;
    - loops containing exits get per-loop break/continue flags and the
      conjunct `not flag` on their predicate; `for _ in range(...)` with
      exits rewrites to an equivalent while (`range_parts` computes the
      trip count, concretely or on-device);
    - the function ends with a site-dispatch chain that re-evaluates the
      k-th return EXPRESSION once, from the frozen locals — no
      return-value carries at all (the reference carries magic-number
      placeholder values instead), so the staged carries are two scalars.

    Because the guards freeze all locals, deferred evaluation is
    observationally equivalent for pure expressions; an impure return
    expression (rare, discouraged under tracing) evaluates at function
    end instead of at the return site. Exits inside with/try, non-range
    for loops, or loop-else clauses abort the rewrite (those statements
    keep today's fall-back behavior)."""

    def __init__(self):
        self.n = 0
        self.sites = []            # [(site_id, value_expr_or_None)]
        self.use_ret = False

    # -- scan for placements the guard rewrite cannot reach
    def _unsupported(self, stmts, in_loop):
        for s in stmts:
            if isinstance(s, _SCOPES):
                continue
            if isinstance(s, (ast.With, ast.AsyncWith, ast.Try)):
                kids = []
                for a in ("body", "orelse", "finalbody"):
                    kids += getattr(s, a, None) or []
                for h in getattr(s, "handlers", ()) or ():
                    kids += h.body
                if _contains(kids, (ast.Return,)):
                    return True
                if in_loop and _contains(kids, (ast.Break, ast.Continue),
                                         skip_loops=True):
                    return True
                continue
            if isinstance(s, (ast.For, ast.AsyncFor)):
                if not _is_simple_range_for(s):
                    if _contains([s], (ast.Return,)):
                        return True
                    continue        # break targeting it stays Python
                if self._unsupported(s.body, True):
                    return True
                continue
            if isinstance(s, ast.While):
                if s.orelse:
                    if _contains([s], (ast.Return,)):
                        return True
                    continue
                if self._unsupported(s.body, True):
                    return True
                continue
            if isinstance(s, ast.If):
                if self._unsupported(s.body, in_loop) \
                        or self._unsupported(s.orelse, in_loop):
                    return True
        return False

    def transform(self, fdef):
        body = fdef.body
        ret_in_compound = any(
            isinstance(s, (ast.If, ast.While, ast.For))
            and _contains([s], (ast.Return,)) for s in body)
        brk_anywhere = self._any_staged_break(body)
        if not (ret_in_compound or brk_anywhere):
            return False
        if self._unsupported(body, False):
            return False
        self.use_ret = ret_in_compound
        falls_through = not _terminates(body)
        new_body, _ = self._rw_list(body, None)
        out = []
        if self.use_ret:
            out += [_assign(_RET, _const(False)), _assign(_SITE, _const(0))]
        out += new_body
        if self.use_ret:
            out += self._dispatch(falls_through)
        fdef.body = [ast.copy_location(s, body[0]) for s in out]
        ast.fix_missing_locations(fdef)
        return True

    def _any_staged_break(self, stmts):
        for s in stmts:
            if isinstance(s, _SCOPES):
                continue
            if isinstance(s, ast.While) and not s.orelse \
                    and _contains(s.body, (ast.Break, ast.Continue),
                                  skip_loops=True):
                return True
            if isinstance(s, ast.For) and _is_simple_range_for(s) \
                    and _contains(s.body, (ast.Break, ast.Continue),
                                  skip_loops=True):
                return True
            for a in ("body", "orelse", "finalbody"):
                if self._any_staged_break(getattr(s, a, None) or []):
                    return True
            for h in getattr(s, "handlers", ()) or ():
                if self._any_staged_break(h.body):
                    return True
        return False

    # -- rewrite
    def _live_flags(self, ctx):
        flags = []
        if ctx is not None:
            flags += [f for f in (ctx.brk, ctx.cont) if f]
        if self.use_ret:
            flags.append(_RET)
        return flags

    def _rw_list(self, stmts, ctx):
        out, may_any = [], False
        for idx, s in enumerate(stmts):
            new, may = self._rw_stmt(s, ctx)
            out.extend(new)
            if may:
                may_any = True
                rest = stmts[idx + 1:]
                if rest:
                    rbody, _ = self._rw_list(rest, ctx)
                    flags = self._live_flags(ctx)
                    loads = [_load(f) for f in flags]
                    test = _not(loads[0] if len(loads) == 1
                                else ast.BoolOp(op=ast.Or(), values=loads))
                    g = ast.If(test=test, body=rbody, orelse=[])
                    g._jst_guard = True   # one-sided assigns merge softly
                    out.append(g)
                return out, True
        return out, may_any

    def _rw_stmt(self, s, ctx):
        if isinstance(s, ast.Return):
            if not self.use_ret:
                return [s], False
            k = len(self.sites) + 1
            self.sites.append((k, s.value))
            return [_assign(_RET, _const(True)),
                    _assign(_SITE, _const(k))], True
        if isinstance(s, ast.Break):
            return [_assign(ctx.brk, _const(True))], True
        if isinstance(s, ast.Continue):
            return [_assign(ctx.cont, _const(True))], True
        if isinstance(s, ast.If):
            nb, mb = self._rw_list(s.body, ctx)
            no, mo = self._rw_list(s.orelse, ctx)
            s.body = nb or [ast.Pass()]
            s.orelse = no
            return [s], mb or mo
        if isinstance(s, ast.While) and not s.orelse:
            return self._rw_while(s, ctx)
        if isinstance(s, ast.For) and _is_simple_range_for(s):
            return self._rw_for_range(s, ctx)
        return [s], False

    def _loop_flags(self, body):
        """(brk_name|None, cont_name|None, ret_in) for a loop body."""
        self.n += 1
        k = self.n
        brk = (f"_jst_brk{k}"
               if _contains(body, (ast.Break,), skip_loops=True) else None)
        cont = (f"_jst_cont{k}"
                if _contains(body, (ast.Continue,), skip_loops=True)
                else None)
        ret_in = self.use_ret and _contains(body, (ast.Return,))
        return brk, cont, ret_in

    def _loop_test(self, orig_test, brk, ret_in):
        conj = []
        if brk:
            conj.append(_not(_load(brk)))
        if ret_in:
            conj.append(_not(_load(_RET)))
        if not conj:
            return orig_test
        return ast.BoolOp(op=ast.And(), values=conj + [orig_test])

    def _rw_while(self, s, outer_ctx):
        brk, cont, ret_in = self._loop_flags(s.body)
        if not (brk or cont or ret_in):
            s.body = self._rw_list(s.body, None)[0]   # nested loops only
            return [s], False
        nb, _ = self._rw_list(s.body, _LoopCtx(brk, cont))
        body = ([_assign(cont, _const(False))] if cont else []) + nb
        s.test = self._loop_test(s.test, brk, ret_in)
        s.body = body
        pre = [_assign(brk, _const(False))] if brk else []
        return pre + [s], ret_in

    def _rw_for_range(self, s, outer_ctx):
        brk, cont, ret_in = self._loop_flags(s.body)
        if not (brk or cont or ret_in):
            s.body = self._rw_list(s.body, None)[0]
            return [s], False
        k = self.n
        base, cnt, stp = f"_jst_fb{k}", f"_jst_fn{k}", f"_jst_fs{k}"
        i = f"_jst_fi{k}"
        parts_call = ast.Call(
            func=ast.Attribute(value=_load(_HELPER), attr="range_parts",
                               ctx=ast.Load()),
            args=list(s.iter.args), keywords=[])
        pre = [ast.Assign(
                   targets=[ast.Tuple(
                       elts=[_store(base), _store(cnt), _store(stp)],
                       ctx=ast.Store())],
                   value=parts_call),
               _assign(i, _const(0))]
        nb, _ = self._rw_list(s.body, _LoopCtx(brk, cont))
        body = ([_assign(cont, _const(False))] if cont else [])
        body.append(ast.Assign(
            targets=[_store(s.target.id)],
            value=ast.BinOp(left=_load(base), op=ast.Add(),
                            right=ast.BinOp(left=_load(i), op=ast.Mult(),
                                            right=_load(stp)))))
        body += nb
        # the increment stays OUTSIDE the continue/break guards: `continue`
        # must still advance the iteration variable, exactly like the
        # Python for it replaces
        body.append(_assign(i, ast.BinOp(left=_load(i), op=ast.Add(),
                                         right=_const(1))))
        test = self._loop_test(
            ast.Compare(left=_load(i), ops=[ast.Lt()],
                        comparators=[_load(cnt)]),
            brk, ret_in)
        loop = ast.While(test=test, body=body, orelse=[])
        pre2 = [_assign(brk, _const(False))] if brk else []
        return pre + pre2 + [loop], ret_in

    # -- final site dispatch
    def _dispatch(self, falls_through):
        sites = self.sites
        if not sites:
            return []
        if falls_through:
            leaf_expr, chain_sites = None, sites
        else:
            leaf_expr, chain_sites = sites[-1][1], sites[:-1]

        # element-wise return values when every site returns a literal
        # tuple of one arity (staged selects need array leaves, not
        # tuple objects)
        arities = set()
        for _, e in sites:
            arities.add(len(e.elts) if isinstance(e, ast.Tuple)
                        else (None if e is None else -1))
        m = next(iter(arities)) if len(arities) == 1 else -1
        if isinstance(m, int) and m is not None and m > 0 \
                and not falls_through:
            rvs = [f"{_RV}_{j}" for j in range(m)]

            def site_assign(e):
                return [ast.Assign(
                    targets=[ast.Tuple(elts=[_store(r) for r in rvs],
                                       ctx=ast.Store())],
                    value=e)]

            ret_stmt = ast.Return(value=ast.Tuple(
                elts=[_load(r) for r in rvs], ctx=ast.Load()))
        else:
            def site_assign(e):
                return [_assign(_RV, e if e is not None else _const(None))]

            ret_stmt = ast.Return(value=_load(_RV))

        cur = site_assign(leaf_expr)
        for k, e in reversed(chain_sites):
            cur = [ast.If(
                test=ast.Compare(left=_load(_SITE), ops=[ast.Eq()],
                                 comparators=[_const(k)]),
                body=site_assign(e), orelse=cur)]
        return cur + [ret_stmt]


_BUILTIN_SKIP = {"range", "len", "print", "isinstance", "issubclass",
                 "getattr", "setattr", "hasattr", "float", "int", "bool",
                 "str", "repr", "type", "enumerate", "zip", "list",
                 "dict", "tuple", "set", "super", "sorted", "reversed",
                 "min", "max", "abs", "sum", "any", "all", "id", "iter",
                 "next", "callable", "vars", "globals", "locals"}


class _CallTransformer(ast.NodeTransformer):
    """`f(...)` -> `__jst.convert_call(f)(...)` at user call sites, making
    conversion TRANSITIVE through calls (ref dy2static convert_call (U)).
    Applied AFTER the statement conversion: it descends into the
    generated `__jst_*` branch/body defs (user code lives there now) but
    leaves genuinely nested user defs alone — those convert at their own
    call sites. Builtin names and `__jst` helpers are skipped to keep the
    eager overhead at one cached dict lookup per user call."""

    def __init__(self):
        self.wrapped = 0

    def _visit_scope(self, node):
        if node.name.startswith("__jst"):
            return self.generic_visit(node)
        return node

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_Call(self, node):
        node = self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in _BUILTIN_SKIP or f.id.startswith("__jst"):
                return node
        elif isinstance(f, ast.Attribute):
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id.startswith("__jst"):
                return node
        else:
            return node             # call-of-call / subscripted callables
        node.func = _helper_call("convert_call", [f])
        self.wrapped += 1
        return node


class _PredicateTransformer(ast.NodeTransformer):
    """Rewrites `and`/`or`/`not` and chained comparisons INSIDE a
    converted statement's test expression into lazy helper calls, so
    traced operands stage (logical_and/or/not) instead of tripping
    Python's bool() — the reference's convert_logical_* rewrite.
    Short-circuit behavior is preserved for concrete values; a CHAINED
    comparison's middle operands may evaluate twice (lite scope). Apply
    via `transform`, which skips tests containing walrus bindings (the
    lambda wrap would capture `:=` in its own scope, hiding the name
    from the branch body)."""

    @classmethod
    def transform(cls, test):
        if any(isinstance(s, ast.NamedExpr) for s in ast.walk(test)):
            return test
        return cls().visit(test)

    def visit_Lambda(self, node):
        return node

    def visit_BoolOp(self, node):
        node = self.generic_visit(node)
        name = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = _helper_call(name, [_lam(v), _lam(out)])
        return out

    def visit_UnaryOp(self, node):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _helper_call("convert_logical_not", [node.operand])
        return node

    def visit_Compare(self, node):
        node = self.generic_visit(node)
        if len(node.ops) == 1:
            return node
        left, pairs = node.left, []
        for op, comp in zip(node.ops, node.comparators):
            pairs.append(ast.Compare(left=left, ops=[op],
                                     comparators=[comp]))
            left = comp
        out = pairs[-1]
        for p in reversed(pairs[:-1]):
            out = _helper_call("convert_logical_and", [_lam(p), _lam(out)])
        return out


class _Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self, filename="<dy2static>", line_base=0):
        self.counter = 0
        self.converted_any = False
        self.guarded = False
        self.filename = filename
        self.line_base = line_base

    # nested scopes keep their own control flow untouched by THIS pass
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def _emit(self, names, defs, helper, k):
        prefix = f"__jst_v{k}_"
        stmts = list(defs)
        stmts += _guarded_reads(names, prefix)
        call = ast.Call(
            func=ast.Attribute(value=_load(_HELPER), attr=helper,
                               ctx=ast.Load()),
            args=[ast.Tuple(elts=[_load(f"{prefix}{i}")
                                  for i in range(len(names))],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load())],
            keywords=[])
        return stmts, call

    def _guard_unconvertible(self, node):
        """Wrap the test of an unconvertible if/while so a TRACED
        predicate raises the shared-diagnostic error instead of jax's
        concretization traceback. Concrete predicates keep exact Python
        semantics (the guard is identity for them)."""
        if getattr(node, "_jst_guard", False):
            return node      # generated flag-guard ifs are ours
        from ..analysis.diagnostics import scan_statement

        reasons = scan_statement(node, include_plain_exits=True)
        if not reasons:
            return node
        node.test = ast.Call(
            func=ast.Attribute(value=_load(_HELPER),
                               attr="unconvertible_guard", ctx=ast.Load()),
            args=[node.test,
                  ast.Constant(value=tuple(
                      (c, self.line_base + ln) for c, ln in reasons)),
                  ast.Constant(value=self.filename),
                  ast.Constant(value=self.line_base + node.lineno)],
            keywords=[])
        ast.copy_location(node.test, node)
        self.guarded = True
        return node

    def visit_If(self, node):
        node = self.generic_visit(node)
        if not _convertible(node):
            return self._guard_unconvertible(node)
        node.test = _PredicateTransformer.transform(node.test)
        k = self.counter = self.counter + 1
        names = _assigned_names(node.body + node.orelse)
        tname, fname = f"__jst_t{k}", f"__jst_f{k}"
        defs = [
            _fn_def(tname, node.body, names, _carries_return(names)),
            _fn_def(fname, node.orelse, names, _carries_return(names)),
        ]
        stmts, call = self._emit(names, defs, "convert_ifelse", k)
        call.args = [node.test, _load(tname), _load(fname)] + call.args
        if getattr(node, "_jst_guard", False):
            call.keywords.append(ast.keyword(
                arg="guard", value=ast.Constant(value=True)))
        if names:
            stmts.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        self.converted_any = True
        return [ast.copy_location(s, node) for s in stmts]

    def visit_For(self, node):
        node = self.generic_visit(node)
        it = node.iter
        if (node.orelse or not isinstance(node.target, ast.Name)
                or not _convertible(node)):
            return node  # for-else / tuple-target / break-carrying: Python
        is_range = _is_range_call(it)
        target = node.target.id
        if target in _assigned_names(node.body):
            # a body that REBINDS the loop target has Python semantics the
            # threaded-target rewrite can't reproduce — leave it alone
            return node
        k = self.counter = self.counter + 1
        names = _assigned_names(node.body)
        bname, inner = f"__jst_fb{k}", f"__jst_inner{k}"
        body = [ast.Assign(
            targets=[ast.Tuple(elts=[_store(target), _store(inner)],
                               ctx=ast.Store())],
            value=_load(_VALS))]
        if names:
            body.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)],
                value=_load(inner)))
        body += node.body
        body.append(_carries_return(names))
        body_def = ast.FunctionDef(name=bname, args=_one_arg(), body=body,
                                   decorator_list=[], returns=None,
                                   type_params=[])
        prior = f"__jst_v{k}_prior"
        helper = "convert_for_range" if is_range else "convert_for_iter"
        stmts, call = self._emit(names, [body_def], helper, k)
        stmts += _guarded_reads([target], prior)       # -> __jst_vK_prior0
        head = (ast.Tuple(elts=list(it.args), ctx=ast.Load()) if is_range
                else it)
        call.args = [head, _load(bname)] + call.args \
            + [ast.Constant(value=target), _load(prior + "0")]
        out = f"__jst_out{k}"
        stmts.append(ast.Assign(
            targets=[ast.Tuple(elts=[_store(target), _store(out)],
                               ctx=ast.Store())],
            value=call))
        if names:
            stmts.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)],
                value=_load(out)))
        self.converted_any = True
        return [ast.copy_location(s, node) for s in stmts]

    def visit_While(self, node):
        node = self.generic_visit(node)
        if node.orelse or not _convertible(node):
            return self._guard_unconvertible(node)  # while/else: Python
        node.test = _PredicateTransformer.transform(node.test)
        k = self.counter = self.counter + 1
        names = _assigned_names(node.body)
        cname, bname = f"__jst_c{k}", f"__jst_b{k}"
        cond_def = ast.FunctionDef(
            name=cname, args=_one_arg(),
            body=([ast.Assign(targets=[_names_tuple(names, ast.Store)],
                              value=_load(_VALS))] if names else [])
            + [ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        defs = [cond_def,
                _fn_def(bname, node.body, names, _carries_return(names))]
        stmts, call = self._emit(names, defs, "convert_while", k)
        call.args = [_load(cname), _load(bname)] + call.args
        if names:
            stmts.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        self.converted_any = True
        return [ast.copy_location(s, node) for s in stmts]


_CONVERT_CACHE = {}

# modules whose callables are never user control-flow candidates: wrapping
# them through the converter would be pure per-call overhead
_SKIP_MODULE_PREFIXES = ("paddle_tpu", "jax", "numpy", "builtins",
                        "functools", "itertools", "operator", "math")


def convert_call(fn):
    """Transitive conversion at CALL SITES (ref dy2static convert_call
    (U)): every call inside a converted function routes through here, so
    helper functions the user did NOT decorate still get their if/while/
    for staged. Framework/builtin callables, classes, Layer instances and
    anything unconvertible pass through untouched; results cache on the
    function object (and by code object inside convert_to_static), so the
    steady-state cost is one attribute lookup."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn                   # builtins, classes, callables, Layers
    if isinstance(fn, types.MethodType):
        # BEFORE the cache: bound methods proxy attribute reads to their
        # __func__, so the plain-function cache entry would come back
        # unbound and the call would drop self — convert the underlying
        # function (its own cache applies) and rebind
        conv = convert_call(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    cached = getattr(fn, "__dy2static_call_cache__", None)
    if cached is not None:
        return cached
    if getattr(fn, "__dy2static_converted__", False) \
            or getattr(fn, "_not_to_static", False):
        return fn
    mod = getattr(fn, "__module__", "") or ""
    if mod.split(".", 1)[0] in _SKIP_MODULE_PREFIXES:
        return fn
    conv = convert_to_static(fn)
    try:
        fn.__dy2static_call_cache__ = conv
    except (AttributeError, TypeError):
        pass                        # bound methods: code-id cache applies
    return conv


def convert_to_static(fn):
    """Return `fn` with its `if`/`while` statements rewritten to runtime
    control-flow dispatch, or `fn` unchanged when there is nothing to
    convert or the source is unavailable. Never raises: to_static must
    keep working on functions this lite converter can't parse. Bound
    methods convert through their underlying function and rebind."""
    if isinstance(fn, types.MethodType):
        conv = convert_to_static(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    if getattr(fn, "_not_to_static", False) \
            or getattr(fn, "__dy2static_converted__", False):
        return fn
    code = getattr(fn, "__code__", None)
    # closure-bearing functions are NEVER cached: the conversion snapshots
    # cell contents into its namespace, and sibling closures share one
    # code object — a cache hit would serve the first sibling's values
    cacheable = code is not None and not fn.__closure__
    if cacheable and id(code) in _CONVERT_CACHE:
        ent = _CONVERT_CACHE[id(code)]
        if ent[0] is code:              # id-recycling guard
            return ent[1] or fn
    converted = _convert_uncached(fn)
    if cacheable:
        # keyed on the CODE OBJECT's id with an identity pin — only
        # concrete function objects reach this store, never tracers
        _CONVERT_CACHE[id(code)] = (code, converted)  # noqa: PTA402
    return converted or fn


def _convert_uncached(fn):
    if not inspect.isfunction(fn):
        return None
    if fn.__code__.co_flags & (inspect.CO_GENERATOR | inspect.CO_COROUTINE
                               | inspect.CO_ASYNC_GENERATOR):
        # yield/await make the return rewrite (and staging generally)
        # meaningless — leave generators and coroutines untouched
        return None
    if "__class__" in fn.__code__.co_freevars:
        # zero-arg super() needs the compiler-provided __class__ cell,
        # which a module-level recompile cannot reproduce — leave such
        # methods unconverted (concrete predicates keep working; traced
        # ones get the standard concretization error)
        return None
    try:
        src_lines, src_start = inspect.getsourcelines(fn)
        src = textwrap.dedent("".join(src_lines))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    has_cf = any(isinstance(n, (ast.If, ast.While, ast.For))
                 for n in ast.walk(fdef))
    has_call = any(isinstance(n, ast.Call) for n in ast.walk(fdef))
    if not (has_cf or has_call):
        return None
    fdef.decorator_list = []       # re-applying the decorator would recurse
    # pass 1: early exits (return/break/continue) -> flag-guarded dataflow
    _EarlyExit().transform(fdef)
    try:
        srcfile = inspect.getsourcefile(fn) or "<dy2static>"
    except TypeError:
        srcfile = "<dy2static>"
    tf = _Dy2StaticTransformer(filename=srcfile, line_base=src_start - 1)
    # transform only the TOP function's statements; visit() on the module
    # would treat the def itself as a nested scope
    fdef.body = [s for stmt in fdef.body
                 for s in _as_list(tf.visit(stmt))]
    # pass 3: transitive conversion — user call sites route through
    # convert_call, so undecorated helpers stage too (a function with no
    # control flow of its own still converts for its call sites)
    ct = _CallTransformer()
    fdef.body = [ct.visit(s) for s in fdef.body]
    if not (tf.converted_any or ct.wrapped or tf.guarded):
        return None
    ast.fix_missing_locations(tree)
    # closure cells: rebuild real cells by wrapping the converted def in a
    # factory whose parameters are the (bound) freevars — values snapshot
    # at conversion time (documented lite-scope trade-off), but the names
    # never leak into module globals. Empty cells (e.g. recursive defs)
    # stay out of the factory so those names fall through to live globals.
    cell_vals = {}
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                cell_vals[name] = cell.cell_contents
            except ValueError:          # empty cell (e.g. recursive def)
                pass
    factory_name = f"__jst_factory_{fn.__name__}"
    # the factory is also needed whenever the body references the
    # function's OWN name (self-recursion) — nested (freevar) or
    # module-level (global load): the def inside the factory rebinds the
    # name in factory scope, so the recursive call hits the CONVERTED
    # function, as the old snapshot-namespace exec did
    use_factory = (bool(cell_vals)
                   or fn.__name__ in fn.__code__.co_freevars
                   or fn.__name__ in fn.__code__.co_names)
    if use_factory:
        # the def itself rebinds fn.__name__ in the factory scope, so a
        # SELF-RECURSIVE nested function (own name = empty cell at
        # decoration time, excluded from the args) resolves to the
        # converted function — like the pre-factory exec namespace did
        factory = ast.FunctionDef(
            name=factory_name,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in cell_vals],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=[fdef, ast.Return(value=_load(fdef.name))],
            decorator_list=[], returns=None, type_params=[])
        tree.body[0] = factory
        ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<dy2static {fn.__name__}>", "exec")
    except (SyntaxError, ValueError):
        return None
    import sys

    helper_mod = sys.modules[__name__]
    # exec with globals = the ORIGINAL fn.__globals__ (so the converted
    # function sees later-defined / rebound module globals live — it must
    # behave like the unconverted function) and a separate locals dict so
    # the def itself never clobbers the module's own bindings. Only the
    # collision-proof `__jst` helper name is injected into live globals;
    # if the module somehow defines `__jst` itself, fall back to an
    # isolated snapshot copy rather than clobbering it.
    glb = fn.__globals__
    if _HELPER in glb and glb[_HELPER] is not helper_mod:
        glb = dict(fn.__globals__)
    glb[_HELPER] = helper_mod
    local_ns = {}
    try:
        exec(code, glb, local_ns)
        if use_factory:
            new_fn = local_ns[factory_name](**cell_vals)
        else:
            new_fn = local_ns.get(fn.__name__)
    except Exception:
        return None
    if not inspect.isfunction(new_fn):
        return None
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn)
    new_fn.__dy2static_converted__ = True
    return new_fn


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]
