"""dy2static-lite: AST conversion of Python `if`/`while` over traced
tensors (ref: python/paddle/jit/dy2static/ (U), SURVEY.md §2.2 P8 — the
reference rewrites dygraph Python control flow into ConditionalBlock /
While ops so `to_static` can compile data-dependent branches).

TPU-native stance: `to_static` is jax tracing, so control flow over
CONCRETE Python values needs no conversion at all (the trace simply
unrolls/specializes, and re-traces per input signature). What tracing
cannot do is a branch or loop whose predicate is a traced tensor — that is
exactly what `static.nn.cond` / `static.nn.while_loop` (lax select +
lax.while_loop) stage. This module closes the gap the reference closes
with its AST transformer, scoped the same way:

- every `if`/`while` statement is rewritten into a call to a runtime
  dispatch helper (`convert_ifelse` / `convert_while`);
- at RUN time the helper inspects the predicate: a plain Python/concrete
  value keeps exact Python semantics (one branch runs, loops run
  eagerly/unroll under trace); a traced or symbolic tensor stages;
- variables assigned in a branch/loop body become explicit carries —
  rebound from a tuple on entry, returned on exit — so the rewrite never
  needs `nonlocal` and AugAssign keeps working;
- names possibly unbound before the statement are carried as an `UNDEF`
  sentinel: a temp defined inside the branch/loop body works, a genuine
  read-before-assignment raises a NameError naming the variable.

Deliberately NOT converted (the statement stays plain Python, which keeps
working for concrete predicates and raises jax's concretization error for
traced ones): `if`/`while` containing `return`, or `break`/`continue`
targeting an enclosing loop, or `del`/`global`/`nonlocal`; `while/else`;
functions whose source is unavailable. Conversion applies to the
decorated function only (not transitively through calls) — decorate
helpers with `paddle.jit.to_static` too, or call `static.nn.cond`
directly.
"""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

__all__ = ["convert_to_static", "convert_ifelse", "convert_while",
           "convert_for_range", "convert_logical_and",
           "convert_logical_or", "convert_logical_not",
           "UndefinedVar", "UNDEF"]


class UndefinedVar:
    """Sentinel carried for names not yet bound when a converted statement
    runs. Any actual USE raises — matching the NameError the untransformed
    code would have raised, just later and with context."""

    __slots__ = ("name",)

    def __init__(self, name="<var>"):
        self.name = name

    def _boom(self):
        raise NameError(
            f"variable {self.name!r} is read on a path through converted "
            "control flow where it was never assigned (dy2static carries "
            "it as undefined); assign it before the if/while")

    def __getattr__(self, item):
        self._boom()

    def __call__(self, *a, **k):
        self._boom()

    def __bool__(self):
        self._boom()

    def __iter__(self):
        self._boom()

    def __repr__(self):
        return f"UndefinedVar({self.name!r})"


# operator dunders are looked up on the TYPE (bypassing __getattr__), so a
# sentinel used in arithmetic/indexing/comparison must trip explicitly
def _undef_op(name):
    def op(self, *a, **k):
        self._boom()
    op.__name__ = name
    return op


for _dunder in ("__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
                "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
                "__rfloordiv__", "__mod__", "__rmod__", "__pow__",
                "__rpow__", "__matmul__", "__rmatmul__", "__neg__",
                "__pos__", "__abs__", "__lt__", "__le__", "__gt__",
                "__ge__", "__getitem__", "__setitem__", "__len__",
                "__float__", "__int__", "__index__", "__contains__"):
    setattr(UndefinedVar, _dunder, _undef_op(_dunder))


UNDEF = UndefinedVar()


def _is_traced(x):
    """True when `x` cannot be bool()-ed: a jax tracer, or a Tensor whose
    value is a tracer / a static-graph symbol."""
    import jax

    from ..core.tensor import Tensor

    if isinstance(x, Tensor):
        x = x._data
    if isinstance(x, jax.core.Tracer):
        return True
    return type(x).__name__ in ("_SymArr", "_GradSym")


def _to_carry(x, name):
    """A loop carry entering the staged path must be an array value."""
    from ..core.tensor import Tensor
    from ..tensor.creation import to_tensor

    if isinstance(x, Tensor):
        return x
    if isinstance(x, (bool, int, float, complex)) or hasattr(x, "dtype"):
        return to_tensor(x)
    raise TypeError(
        f"variable {name!r} of type {type(x).__name__} cannot be carried "
        "through staged control flow (only tensors and numbers can); hoist "
        "it out of the if/while or keep the predicate concrete")


def convert_ifelse(pred, true_fn, false_fn, vals, names):
    """Runtime dispatch for a converted `if`: concrete predicate keeps
    exact Python semantics (one branch runs); traced predicate builds both
    branches and stages a select per assigned variable."""
    from ..core.tensor import Tensor

    if isinstance(pred, UndefinedVar):
        pred._boom()
    if not _is_traced(pred):
        if isinstance(pred, Tensor):
            pred = bool(pred)
        return true_fn(vals) if pred else false_fn(vals)

    from ..static.nn import cond as static_cond

    if not names:
        # a branch that binds no names can only act through side effects
        # (list.append, dict/attr mutation) — under a traced predicate
        # BOTH branches would execute unconditionally, so wrong results
        # would silently replace the loud pre-conversion error
        raise TypeError(
            "a converted `if` over a traced tensor predicate assigns no "
            "variables — its body works only by side effects, which "
            "cannot be staged (both branches trace). Assign the result "
            "to a variable, or call paddle.static.nn.cond directly.")
    # tracing: both branches run (the reference records both
    # ConditionalBlocks too); outputs merge by a staged select
    t_out = true_fn(vals)
    f_out = false_fn(vals)
    sel_idx, t_sel, f_sel = [], [], []
    merged = [None] * len(names)
    for i, (tv, fv, name) in enumerate(zip(t_out, f_out, names)):
        t_undef = isinstance(tv, UndefinedVar)
        f_undef = isinstance(fv, UndefinedVar)
        if t_undef and f_undef:
            merged[i] = UndefinedVar(name)      # stays undefined, loudly
        elif t_undef or f_undef:
            # defined on one path only: usable downstream on neither
            # (staged code runs once) — bind the loud sentinel
            merged[i] = UndefinedVar(name)
        elif tv is fv:
            merged[i] = tv                      # untouched by both
        else:
            sel_idx.append(i)
            t_sel.append(tv)
            f_sel.append(fv)
    if sel_idx:
        # the branch lambdas return tuples, so cond rebuilds a tuple of
        # the same arity (including arity 1)
        picked = static_cond(pred, lambda: tuple(t_sel),
                             lambda: tuple(f_sel))
        for i, v in zip(sel_idx, picked):
            merged[i] = v
    return tuple(merged)


def convert_while(cond_fn, body_fn, vals, names):
    """Runtime dispatch for a converted `while`: a concrete first
    predicate runs the plain Python loop (which unrolls under trace — jax
    semantics for concrete trip counts); a traced predicate stages ONE
    lax.while_loop over the defined carries. Names unbound before the
    loop are NOT carried across iterations: a temp assigned-then-used
    within one body iteration works, a genuine cross-iteration read
    raises a NameError naming the variable."""
    first = cond_fn(vals)
    if isinstance(first, UndefinedVar):
        first._boom()
    if not _is_traced(first):
        from ..core.tensor import Tensor

        def as_bool(p):
            return bool(p) if isinstance(p, Tensor) else p

        p = as_bool(first)
        while p:
            vals = body_fn(vals)
            nxt = cond_fn(vals)
            if _is_traced(nxt):
                raise TypeError(
                    "while predicate became a traced tensor after the "
                    "first iteration; make it traced from the start (so "
                    "the loop stages) or keep it concrete throughout")
            p = as_bool(nxt)
        return vals

    from ..static.nn import while_loop as static_while

    keep = [i for i, v in enumerate(vals)
            if not isinstance(v, UndefinedVar)]
    if not keep:
        raise TypeError(
            "a converted `while` over a traced tensor predicate carries "
            "no defined variables — initialize the loop state before the "
            "loop (lax.while_loop needs loop-carried values), or call "
            "paddle.static.nn.while_loop directly.")
    carried = [_to_carry(vals[i], names[i]) for i in keep]

    def full(vs):
        out = list(vals)
        for i, v in zip(keep, vs):
            out[i] = v
        for i in range(len(out)):
            if isinstance(out[i], UndefinedVar):
                out[i] = UndefinedVar(names[i])
        return tuple(out)

    def body_w(*vs):
        res = body_fn(full(vs))
        out = []
        for i in keep:
            v = res[i]
            if isinstance(v, UndefinedVar):
                v._boom()
            out.append(v)
        return out

    outs = static_while(lambda *vs: cond_fn(full(vs)), body_w, carried)
    if len(carried) == 1 and not isinstance(outs, (tuple, list)):
        outs = [outs]
    final = list(vals)
    for i, v in zip(keep, outs):
        final[i] = v
    for i in range(len(final)):
        if isinstance(final[i], UndefinedVar):
            final[i] = UndefinedVar(names[i])
    return tuple(final)


def convert_logical_and(lx, ly):
    """`a and b` inside a converted statement's predicate (ref
    convert_logical_and): Python short-circuit semantics for concrete
    values; traced values evaluate BOTH sides and stage logical_and
    (the reference's behavior — no short-circuit once staged)."""
    x = lx()
    if isinstance(x, UndefinedVar):
        x._boom()
    if not _is_traced(x):
        if not x:
            return x
        return ly()
    y = ly()
    from ..tensor.logic import logical_and

    return logical_and(_to_carry(x, "<and-lhs>").astype("bool"),
                       _to_carry(y, "<and-rhs>").astype("bool"))


def convert_logical_or(lx, ly):
    x = lx()
    if isinstance(x, UndefinedVar):
        x._boom()
    if not _is_traced(x):
        if x:
            return x
        return ly()
    y = ly()
    from ..tensor.logic import logical_or

    return logical_or(_to_carry(x, "<or-lhs>").astype("bool"),
                      _to_carry(y, "<or-rhs>").astype("bool"))


def convert_logical_not(x):
    if isinstance(x, UndefinedVar):
        x._boom()
    if not _is_traced(x):
        return not x
    from ..tensor.logic import logical_not

    return logical_not(_to_carry(x, "<not-operand>").astype("bool"))


def convert_for_range(range_args, body_fn, vals, names,
                      target_name="<target>", target_prior=UNDEF):
    """Runtime dispatch for a converted `for <target> in range(...)`:
    concrete bounds run the plain Python loop (unrolls under trace); a
    traced bound stages ONE lax while_loop with the trip count computed
    on-device. body_fn((i, vals)) -> vals. Returns (final_i, vals) —
    after an EMPTY range the target keeps its prior binding
    (`target_prior`, Python semantics; after a staged empty range that
    only works when the prior value is a tensor/number — otherwise the
    target pins to `start`). Carries follow convert_while's rules
    (undefined names drop out of the carry; cross-iteration reads raise
    by name)."""
    if len(range_args) == 1:
        start, stop, step = 0, range_args[0], 1
    elif len(range_args) == 2:
        start, stop = range_args
        step = 1
    else:
        start, stop, step = range_args

    from ..core.tensor import Tensor

    if isinstance(step, (int, Tensor)) and not _is_traced(step) \
            and int(step) == 0:
        raise ValueError("range() arg 3 must not be zero")

    if not any(_is_traced(v) for v in (start, stop, step)):
        as_py = [int(v) if isinstance(v, Tensor) else v
                 for v in (start, stop, step)]
        # empty range keeps the prior binding; an unbound prior stays the
        # loud sentinel, renamed so the eventual NameError names the var
        i = (UndefinedVar(target_name)
             if isinstance(target_prior, UndefinedVar) else target_prior)
        for i in range(*as_py):
            vals = body_fn((i, vals))
        return i, vals

    import jax.numpy as jnp

    from ..static.nn import while_loop as static_while
    from ..tensor.creation import to_tensor

    def arr(v):
        return v._data if isinstance(v, Tensor) else jnp.asarray(v)

    start_a, stop_a, step_a = arr(start), arr(stop), arr(step)
    # integer sign-aware ceil-div: a float32 round-trip loses exactness
    # at |bounds| >= 2^24 (one lost iteration at 16777217)
    n_pos = (stop_a - start_a + step_a - 1) // step_a
    n_neg = (start_a - stop_a - step_a - 1) // (-step_a)
    n_iters = jnp.maximum(
        0, jnp.where(step_a > 0, n_pos, n_neg)).astype(jnp.int32)

    keep = [i for i, v in enumerate(vals)
            if not isinstance(v, UndefinedVar)]

    def full(vs):
        out = list(vals)
        for i, v in zip(keep, vs):
            out[i] = v
        for i in range(len(out)):
            if isinstance(out[i], UndefinedVar):
                out[i] = UndefinedVar(names[i])
        return tuple(out)

    def cond_w(k, i, *vs):
        from ..core.tensor import Tensor

        kd = k._data if isinstance(k, Tensor) else k
        return Tensor(kd < n_iters)

    def body_w(k, i, *vs):
        res = body_fn((i, full(vs)))
        out = []
        for j in keep:
            v = res[j]
            if isinstance(v, UndefinedVar):
                v._boom()
            out.append(v)
        return [k + 1, i + to_tensor(step_a)] + out

    carried = [_to_carry(vals[i], names[i]) for i in keep]
    outs = static_while(cond_w, body_w,
                        [to_tensor(jnp.zeros((), jnp.int32)),
                         to_tensor(start_a)] + carried)
    final_i = outs[1] - to_tensor(step_a)  # last iterated value...
    # ...except for an empty range, where Python keeps the target's prior
    # binding — honored when the prior is array-valued; otherwise the
    # staged code pins it to `start` deterministically
    from ..core.op_call import apply as _apply

    if isinstance(target_prior, (Tensor, int, float)) \
            and not isinstance(target_prior, bool):
        empty_val = arr(target_prior).astype(start_a.dtype)
    else:
        empty_val = start_a
    final_i = _apply(
        lambda n, fi, st: jnp.where(n > 0, fi, st),
        to_tensor(n_iters), final_i, to_tensor(empty_val),
        _op_name="for_range_final")
    final = list(vals)
    for i, v in zip(keep, outs[2:]):
        final[i] = v
    for i in range(len(final)):
        if isinstance(final[i], UndefinedVar):
            final[i] = UndefinedVar(names[i])
    return final_i, tuple(final)


# --------------------------------------------------------------------------
# AST transformation


_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _assigned_names(stmts):
    """Names bound by the statement list, in first-assignment order.
    Mutations through subscripts/attributes are not bindings; nested
    function/class bodies and comprehensions have their own scope."""
    out, seen = [], set()

    def add(name):
        if not name.startswith("__jst") and name not in seen:
            seen.add(name)
            out.append(name)

    def target_names(t):
        if isinstance(t, ast.Name):
            add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                target_names(e)
        elif isinstance(t, ast.Starred):
            target_names(t.value)

    def walk(body):
        for node in body:
            if isinstance(node, _SCOPES):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    add(node.name)
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    target_names(t)
            elif isinstance(node, ast.AugAssign):
                target_names(node.target)
            elif isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    target_names(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                target_names(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        target_names(item.optional_vars)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    add((a.asname or a.name).split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    add(a.asname or a.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.NamedExpr) \
                        and isinstance(sub.target, ast.Name):
                    add(sub.target.id)
            for attr in ("body", "orelse", "finalbody"):
                child = getattr(node, attr, None)
                if child:
                    walk(child)
            for h in getattr(node, "handlers", ()) or ():
                walk(h.body)

    walk(list(stmts))
    return out


def _contains(stmts, kinds, skip_loops=False):
    """Any node of `kinds` in the statement list, not counting nested
    function/class scopes; with skip_loops, nested for/while bodies are
    skipped too (their break/continue belong to them)."""
    for node in stmts:
        if isinstance(node, _SCOPES):
            continue
        if isinstance(node, kinds):
            return True
        if skip_loops and isinstance(node, (ast.For, ast.AsyncFor,
                                            ast.While)):
            children = list(node.orelse)      # loop else runs after the loop
        else:
            children = []
            for a in ("body", "orelse", "finalbody"):
                children += getattr(node, a, None) or []
            for h in getattr(node, "handlers", ()) or ():
                children += h.body
        if children and _contains(children, kinds, skip_loops):
            return True
    return False


def _convertible(node):
    for body in (node.body, getattr(node, "orelse", [])):
        if not body:
            continue
        if _contains(body, (ast.Return, ast.Delete, ast.Global,
                            ast.Nonlocal)):
            return False
        if _contains(body, (ast.Break, ast.Continue), skip_loops=True):
            return False
    return True


_HELPER = "__jst"
_VALS = "__jst_vals"


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _names_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names],
                     ctx=ctx())


def _one_arg():
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=_VALS)],
                         vararg=None, kwonlyargs=[], kw_defaults=[],
                         kwarg=None, defaults=[])


def _fn_def(name, body_stmts, carry_names, tail):
    """def <name>(__jst_vals): (a,b)=__jst_vals; <body>; <tail>"""
    body = []
    if carry_names:
        body.append(ast.Assign(targets=[_names_tuple(carry_names,
                                                     ast.Store)],
                               value=_load(_VALS)))
    body += body_stmts or [ast.Pass()]
    body.append(tail)
    return ast.FunctionDef(name=name, args=_one_arg(), body=body,
                           decorator_list=[], returns=None, type_params=[])


def _carries_return(names):
    return ast.Return(value=ast.Tuple(elts=[_load(n) for n in names],
                                      ctx=ast.Load()))


def _guarded_reads(names, prefix):
    """try: __jst_vN_i = a / except NameError: ... = __jst.UNDEF — reads
    the current value of each carry without tripping on unbound locals."""
    stmts = []
    undef = ast.Attribute(value=_load(_HELPER), attr="UNDEF",
                          ctx=ast.Load())
    for i, n in enumerate(names):
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[_store(f"{prefix}{i}")],
                             value=_load(n))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_load("NameError"),
                                     _load("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(targets=[_store(f"{prefix}{i}")],
                                 value=undef)])],
            orelse=[], finalbody=[]))
    return stmts


def _lam(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=expr)


def _helper_call(name, args):
    return ast.Call(
        func=ast.Attribute(value=_load(_HELPER), attr=name,
                           ctx=ast.Load()),
        args=args, keywords=[])


class _PredicateTransformer(ast.NodeTransformer):
    """Rewrites `and`/`or`/`not` and chained comparisons INSIDE a
    converted statement's test expression into lazy helper calls, so
    traced operands stage (logical_and/or/not) instead of tripping
    Python's bool() — the reference's convert_logical_* rewrite.
    Short-circuit behavior is preserved for concrete values; a CHAINED
    comparison's middle operands may evaluate twice (lite scope). Apply
    via `transform`, which skips tests containing walrus bindings (the
    lambda wrap would capture `:=` in its own scope, hiding the name
    from the branch body)."""

    @classmethod
    def transform(cls, test):
        if any(isinstance(s, ast.NamedExpr) for s in ast.walk(test)):
            return test
        return cls().visit(test)

    def visit_Lambda(self, node):
        return node

    def visit_BoolOp(self, node):
        node = self.generic_visit(node)
        name = ("convert_logical_and" if isinstance(node.op, ast.And)
                else "convert_logical_or")
        out = node.values[-1]
        for v in reversed(node.values[:-1]):
            out = _helper_call(name, [_lam(v), _lam(out)])
        return out

    def visit_UnaryOp(self, node):
        node = self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _helper_call("convert_logical_not", [node.operand])
        return node

    def visit_Compare(self, node):
        node = self.generic_visit(node)
        if len(node.ops) == 1:
            return node
        left, pairs = node.left, []
        for op, comp in zip(node.ops, node.comparators):
            pairs.append(ast.Compare(left=left, ops=[op],
                                     comparators=[comp]))
            left = comp
        out = pairs[-1]
        for p in reversed(pairs[:-1]):
            out = _helper_call("convert_logical_and", [_lam(p), _lam(out)])
        return out


class _Dy2StaticTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.converted_any = False

    # nested scopes keep their own control flow untouched by THIS pass
    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def _emit(self, names, defs, helper, k):
        prefix = f"__jst_v{k}_"
        stmts = list(defs)
        stmts += _guarded_reads(names, prefix)
        call = ast.Call(
            func=ast.Attribute(value=_load(_HELPER), attr=helper,
                               ctx=ast.Load()),
            args=[ast.Tuple(elts=[_load(f"{prefix}{i}")
                                  for i in range(len(names))],
                            ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Constant(value=n) for n in names],
                            ctx=ast.Load())],
            keywords=[])
        return stmts, call

    def visit_If(self, node):
        node = self.generic_visit(node)
        if not _convertible(node):
            return node
        node.test = _PredicateTransformer.transform(node.test)
        k = self.counter = self.counter + 1
        names = _assigned_names(node.body + node.orelse)
        tname, fname = f"__jst_t{k}", f"__jst_f{k}"
        defs = [
            _fn_def(tname, node.body, names, _carries_return(names)),
            _fn_def(fname, node.orelse, names, _carries_return(names)),
        ]
        stmts, call = self._emit(names, defs, "convert_ifelse", k)
        call.args = [node.test, _load(tname), _load(fname)] + call.args
        if names:
            stmts.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        self.converted_any = True
        return [ast.copy_location(s, node) for s in stmts]

    def visit_For(self, node):
        node = self.generic_visit(node)
        it = node.iter
        if (node.orelse or not isinstance(it, ast.Call)
                or not isinstance(it.func, ast.Name)
                or it.func.id != "range" or it.keywords
                or not (1 <= len(it.args) <= 3)
                or any(isinstance(a, ast.Starred) for a in it.args)
                or not isinstance(node.target, ast.Name)
                or not _convertible(node)):
            return node  # non-range / for-else / break-carrying stays Python
        target = node.target.id
        if target in _assigned_names(node.body):
            # a body that REBINDS the loop target has Python semantics the
            # threaded-target rewrite can't reproduce — leave it alone
            return node
        k = self.counter = self.counter + 1
        names = _assigned_names(node.body)
        bname, inner = f"__jst_fb{k}", f"__jst_inner{k}"
        body = [ast.Assign(
            targets=[ast.Tuple(elts=[_store(target), _store(inner)],
                               ctx=ast.Store())],
            value=_load(_VALS))]
        if names:
            body.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)],
                value=_load(inner)))
        body += node.body
        body.append(_carries_return(names))
        body_def = ast.FunctionDef(name=bname, args=_one_arg(), body=body,
                                   decorator_list=[], returns=None,
                                   type_params=[])
        prior = f"__jst_v{k}_prior"
        stmts, call = self._emit(names, [body_def], "convert_for_range", k)
        stmts += _guarded_reads([target], prior)       # -> __jst_vK_prior0
        call.args = [ast.Tuple(elts=list(it.args), ctx=ast.Load()),
                     _load(bname)] + call.args \
            + [ast.Constant(value=target), _load(prior + "0")]
        out = f"__jst_out{k}"
        stmts.append(ast.Assign(
            targets=[ast.Tuple(elts=[_store(target), _store(out)],
                               ctx=ast.Store())],
            value=call))
        if names:
            stmts.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)],
                value=_load(out)))
        self.converted_any = True
        return [ast.copy_location(s, node) for s in stmts]

    def visit_While(self, node):
        node = self.generic_visit(node)
        if node.orelse or not _convertible(node):
            return node  # while/else stays Python
        node.test = _PredicateTransformer.transform(node.test)
        k = self.counter = self.counter + 1
        names = _assigned_names(node.body)
        cname, bname = f"__jst_c{k}", f"__jst_b{k}"
        cond_def = ast.FunctionDef(
            name=cname, args=_one_arg(),
            body=([ast.Assign(targets=[_names_tuple(names, ast.Store)],
                              value=_load(_VALS))] if names else [])
            + [ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        defs = [cond_def,
                _fn_def(bname, node.body, names, _carries_return(names))]
        stmts, call = self._emit(names, defs, "convert_while", k)
        call.args = [_load(cname), _load(bname)] + call.args
        if names:
            stmts.append(ast.Assign(
                targets=[_names_tuple(names, ast.Store)], value=call))
        else:
            stmts.append(ast.Expr(value=call))
        self.converted_any = True
        return [ast.copy_location(s, node) for s in stmts]


_CONVERT_CACHE = {}


def convert_to_static(fn):
    """Return `fn` with its `if`/`while` statements rewritten to runtime
    control-flow dispatch, or `fn` unchanged when there is nothing to
    convert or the source is unavailable. Never raises: to_static must
    keep working on functions this lite converter can't parse. Bound
    methods convert through their underlying function and rebind."""
    if isinstance(fn, types.MethodType):
        conv = convert_to_static(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    if getattr(fn, "_not_to_static", False):
        return fn
    code = getattr(fn, "__code__", None)
    # closure-bearing functions are NEVER cached: the conversion snapshots
    # cell contents into its namespace, and sibling closures share one
    # code object — a cache hit would serve the first sibling's values
    cacheable = code is not None and not fn.__closure__
    if cacheable and id(code) in _CONVERT_CACHE:
        ent = _CONVERT_CACHE[id(code)]
        if ent[0] is code:              # id-recycling guard
            return ent[1] or fn
    converted = _convert_uncached(fn)
    if cacheable:
        _CONVERT_CACHE[id(code)] = (code, converted)
    return converted or fn


def _convert_uncached(fn):
    if not inspect.isfunction(fn):
        return None
    if "__class__" in fn.__code__.co_freevars:
        # zero-arg super() needs the compiler-provided __class__ cell,
        # which a module-level recompile cannot reproduce — leave such
        # methods unconverted (concrete predicates keep working; traced
        # ones get the standard concretization error)
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    if not any(isinstance(n, (ast.If, ast.While, ast.For))
               for n in ast.walk(fdef)):
        return None
    fdef.decorator_list = []       # re-applying the decorator would recurse
    tf = _Dy2StaticTransformer()
    # transform only the TOP function's statements; visit() on the module
    # would treat the def itself as a nested scope
    fdef.body = [s for stmt in fdef.body
                 for s in _as_list(tf.visit(stmt))]
    if not tf.converted_any:
        return None
    ast.fix_missing_locations(tree)
    # closure cells: rebuild real cells by wrapping the converted def in a
    # factory whose parameters are the (bound) freevars — values snapshot
    # at conversion time (documented lite-scope trade-off), but the names
    # never leak into module globals. Empty cells (e.g. recursive defs)
    # stay out of the factory so those names fall through to live globals.
    cell_vals = {}
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                cell_vals[name] = cell.cell_contents
            except ValueError:          # empty cell (e.g. recursive def)
                pass
    factory_name = f"__jst_factory_{fn.__name__}"
    # the factory is also needed whenever the body references the
    # function's OWN name (self-recursion) — nested (freevar) or
    # module-level (global load): the def inside the factory rebinds the
    # name in factory scope, so the recursive call hits the CONVERTED
    # function, as the old snapshot-namespace exec did
    use_factory = (bool(cell_vals)
                   or fn.__name__ in fn.__code__.co_freevars
                   or fn.__name__ in fn.__code__.co_names)
    if use_factory:
        # the def itself rebinds fn.__name__ in the factory scope, so a
        # SELF-RECURSIVE nested function (own name = empty cell at
        # decoration time, excluded from the args) resolves to the
        # converted function — like the pre-factory exec namespace did
        factory = ast.FunctionDef(
            name=factory_name,
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in cell_vals],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=[fdef, ast.Return(value=_load(fdef.name))],
            decorator_list=[], returns=None, type_params=[])
        tree.body[0] = factory
        ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<dy2static {fn.__name__}>", "exec")
    except (SyntaxError, ValueError):
        return None
    import sys

    helper_mod = sys.modules[__name__]
    # exec with globals = the ORIGINAL fn.__globals__ (so the converted
    # function sees later-defined / rebound module globals live — it must
    # behave like the unconverted function) and a separate locals dict so
    # the def itself never clobbers the module's own bindings. Only the
    # collision-proof `__jst` helper name is injected into live globals;
    # if the module somehow defines `__jst` itself, fall back to an
    # isolated snapshot copy rather than clobbering it.
    glb = fn.__globals__
    if _HELPER in glb and glb[_HELPER] is not helper_mod:
        glb = dict(fn.__globals__)
    glb[_HELPER] = helper_mod
    local_ns = {}
    try:
        exec(code, glb, local_ns)
        if use_factory:
            new_fn = local_ns[factory_name](**cell_vals)
        else:
            new_fn = local_ns.get(fn.__name__)
    except Exception:
        return None
    if not inspect.isfunction(new_fn):
        return None
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn)
    new_fn.__dy2static_converted__ = True
    return new_fn


def _as_list(x):
    if x is None:
        return []
    return x if isinstance(x, list) else [x]
