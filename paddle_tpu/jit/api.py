"""paddle.jit parity: to_static, save, load, TrainStep.

Reference mapping (SURVEY.md §3.4): the dy2static AST/bytecode translator +
ProgramDesc + InterpreterCore + CINN pipeline collapses to `jax.jit` — the
tape-based eager ops are themselves traceable, so tracing the user's Python
callable once yields the whole fwd(+bwd+step) as one XLA program. What remains
of the subsystem is the ergonomics: input-spec caching, state
functionalization (parameters/buffers in, updated buffers out), RNG threading,
and save/load of compiled artifacts via jax.export (the .pdmodel analog is a
serialized StableHLO module).
"""

from __future__ import annotations

import functools
import os
import pickle
import time

import numpy as np
import jax
import jax.export  # noqa: F401 — jax 0.4.x only binds jax.export on
# explicit submodule import; attribute access alone raises AttributeError
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import tape as _tape
from ..core import random_state
from ..nn.layer.layers import Layer
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

# per-function compile/cache telemetry: the acceptance invariant is that
# calling a jitted fn twice with identical avals shows cache_hit += 1 and
# compile_count unchanged (see tests/test_observability.py)
_COMPILE_COUNT = _obs_metrics.counter(
    "jit.compile_count", "to_static trace+compile builds, by function")
_CACHE_HIT = _obs_metrics.counter(
    "jit.cache_hit", "to_static calls served from the jit cache")
_COMPILE_SECONDS = _obs_metrics.histogram(
    "jit.compile_seconds",
    "wall seconds from cache miss to first result, by function")


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        from ..core.dtype import to_jax_dtype

        self.shape = list(shape)
        self.dtype = to_jax_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _spec_key(args, kwargs):
    def leaf_key(a):
        if isinstance(a, Tensor):
            return ("T", tuple(a._data.shape), str(a._data.dtype))
        if isinstance(a, (np.ndarray,)):
            return ("A", a.shape, str(a.dtype))
        if isinstance(a, (list, tuple)):
            return tuple(leaf_key(x) for x in a)
        return ("S", repr(a))

    return (tuple(leaf_key(a) for a in args),
            tuple(sorted((k, leaf_key(v)) for k, v in kwargs.items())))


class StaticFunction:
    """Callable wrapping fn (optionally bound to a Layer) with jit caching."""

    def __init__(self, function, layer=None, input_spec=None, full_graph=True):
        self._fn = function
        if full_graph:
            # dy2static-lite (ref dy2static AST transform, SURVEY.md §2.2
            # P8): if/while over traced tensors stage via lax cond/while;
            # falls back to the original fn when nothing converts
            from .dy2static import convert_to_static

            self._fn = convert_to_static(function)
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        functools.update_wrapper(self, function)

    @property
    def function(self):
        return self._fn

    def concrete_program_specified_input_spec(self, *a, **k):
        return None

    def _build(self, tree_args, tree_kwargs):
        layer = self._layer
        fn = self._fn

        state_names = list(layer.state_dict().keys()) if layer is not None else []

        def array_fn(rng_key, state_arrays, *flat_arrays):
            args, kwargs = _unflatten_args(tree_args, tree_kwargs, flat_arrays)
            with random_state.fork_rng(rng_key):
                if layer is not None:
                    arrays = dict(zip(state_names, state_arrays))
                    with layer.use_state(arrays):
                        out = fn(*args, **kwargs)
                        new_state = [layer.state_dict()[k]._data for k in state_names]
                else:
                    out = fn(*args, **kwargs)
                    new_state = []
            # trace-time mutation detection: a state entry the forward
            # leaves alone is the SAME tracer object it was handed — only
            # genuinely rewritten entries need writing back at call time
            mutated = tuple(i for i, (n, s)
                            in enumerate(zip(new_state, state_arrays))
                            if n is not s)
            out_flat, out_tree = _flatten_out(out)
            return (tuple(o._data if isinstance(o, Tensor) else o for o in out_flat),
                    tuple(new_state), out_tree, mutated)

        # out_tree / mutation set are trace-time static, captured per
        # TRACE: one StaticFunction cache entry can hold several jax.jit
        # traces (state arrays are not part of _spec_key — e.g. amp
        # rebinds a buffer's dtype), so the capture is a dict keyed by
        # the full input aval signature. A single last-trace box would
        # apply a stale mutated-index set when calls alternate between
        # cached signatures (ADVICE r5).
        out_tree_box = {}

        def jittable(rng_key, state_arrays, *flat_arrays):
            outs, new_state, out_tree, mutated = array_fn(
                rng_key, state_arrays, *flat_arrays)
            out_tree_box[_aval_sig(state_arrays, flat_arrays)] = {
                "tree": out_tree, "mutated": mutated}
            return outs, new_state

        return jax.jit(jittable), out_tree_box, state_names

    def __call__(self, *args, **kwargs):
        key = _spec_key(args, kwargs)
        fn_name = getattr(self, "__name__", None) \
            or getattr(self._fn, "__name__", "fn")
        if key in self._cache:
            _CACHE_HIT.inc(fn=fn_name)
            return self._call_impl(key, args, kwargs)
        # miss: a fresh trace+compile — record WHY (first call vs a new
        # input signature, the retrace cause) and how long the whole
        # miss-path call takes (trace + XLA compile + first execution:
        # the user-felt time-to-first-result)
        _obs_events.instant(
            "jit.retrace", cat="jit", fn=fn_name,
            cause=("first_call" if not self._cache
                   else "new_input_signature"),
            cached_signatures=len(self._cache),
            signature=repr(key)[:300])
        _obs_events.begin("jit.compile", cat="jit", fn=fn_name,
                          signature=repr(key)[:300])
        t0 = time.perf_counter()
        try:
            return self._call_impl(key, args, kwargs)
        finally:
            dt = time.perf_counter() - t0
            _COMPILE_COUNT.inc(fn=fn_name)
            _COMPILE_SECONDS.observe(dt, fn=fn_name)
            _obs_events.end("jit.compile", cat="jit", fn=fn_name,
                            seconds=round(dt, 9))

    def _call_impl(self, key, args, kwargs):
        if key not in self._cache:
            tree_args, tree_kwargs = _make_tree(args, kwargs)
            self._cache[key] = self._build(tree_args, tree_kwargs)
        jitted, out_tree_box, state_names = self._cache[key]

        flat, flat_tensors = _flatten_pairs(args, kwargs)
        rng_key = random_state.next_key()
        if self._layer is not None:
            sd = self._layer.state_dict()
            state_tensors = [sd[k] for k in state_names]
        else:
            state_tensors = []
        state_arrays = [t._data for t in state_tensors]
        sig = _aval_sig(state_arrays, flat)

        # ---- grad-aware path (paddle parity: a to_static model trains
        # with eager loss.backward()): the WHOLE jitted forward records as
        # ONE tape node — jax.vjp through the jit call gives the pullback,
        # so grads flow to the layer's parameters and to differentiable
        # inputs exactly as in the unjitted forward.
        from ..core import tape as _tape
        from ..core.op_call import _is_float, apply as _apply

        diff_state_idx = [i for i, t in enumerate(state_tensors)
                         if not t.stop_gradient
                         and _is_float(t._data.dtype)]
        diff_arg_idx = [i for i, t in enumerate(flat_tensors)
                        if t is not None and not t.stop_gradient
                        and _is_float(t._data.dtype)]
        if _tape.tape_enabled() and (diff_state_idx or diff_arg_idx):
            n_s = len(diff_state_idx)

            def call_fn(*arrays):
                st = list(state_arrays)
                fl = list(flat)
                for j, i in enumerate(diff_state_idx):
                    st[i] = arrays[j]
                for j, i in enumerate(diff_arg_idx):
                    fl[i] = arrays[n_s + j]
                outs, new_state = jitted(rng_key, st, *fl)
                return tuple(outs) + tuple(new_state)

            call_fn.__name__ = "to_static_" + getattr(self._fn, "__name__",
                                                      "fn")
            diff_tensors = ([state_tensors[i] for i in diff_state_idx]
                            + [flat_tensors[i] for i in diff_arg_idx])
            res = _apply(call_fn, *diff_tensors, _op_name=call_fn.__name__)
            if not isinstance(res, tuple):
                res = (res,)
            n_out = len(res) - len(state_names)
            out_tensors = list(res[:n_out])
            box = out_tree_box[sig]
            mutated = set(box["mutated"])
            for si, (t, new) in enumerate(zip(state_tensors, res[n_out:])):
                if t.stop_gradient or si in mutated:
                    # buffers (BN stats, ...) update in place; params write
                    # back ONLY when the traced forward actually rewrote
                    # them (advisor r4: dropping a param mutation here
                    # diverged from the no-grad path). Grads still flow
                    # w.r.t. the forward-time values.
                    t._data = new._data
            return _unflatten_tree(box["tree"], out_tensors)

        outs, new_state = jitted(rng_key, state_arrays, *flat)
        for t, arr in zip(state_tensors, new_state):
            t._data = arr
        out_tensors = [Tensor(o) for o in outs]
        return _unflatten_tree(out_tree_box[sig]["tree"], out_tensors)

    # paddle API surface
    def get_concrete_program(self, *args, **kwargs):
        return None

    @property
    def program_cache(self):
        return self._cache


def _aval_sig(state_arrays, flat_arrays):
    """Shape/dtype signature of one jitted-call's inputs — works on both
    concrete arrays (call time) and tracers (trace time), so the capture
    written under trace is found again by the call that triggered it."""
    return (tuple((tuple(a.shape), str(a.dtype)) for a in state_arrays),
            tuple((tuple(a.shape), str(a.dtype)) for a in flat_arrays))


def _make_tree(args, kwargs):
    """Record positions of Tensors; everything else is a static constant."""

    def conv(a):
        if isinstance(a, Tensor):
            return ("leaf",)
        if isinstance(a, np.ndarray):
            return ("leaf_np",)
        if isinstance(a, (list, tuple)):
            return ("seq", type(a).__name__, [conv(x) for x in a])
        return ("const", a)

    return [conv(a) for a in args], {k: conv(v) for k, v in kwargs.items()}


def _flatten_pairs(args, kwargs):
    """ONE walk producing aligned (arrays, tensor-objects-or-None) lists —
    the grad-aware call path maps indices between them, so they must never
    diverge by leaf kind."""
    arrays, tensors = [], []

    def walk(a):
        if isinstance(a, Tensor):
            arrays.append(a._data)
            tensors.append(a)
        elif isinstance(a, np.ndarray):
            arrays.append(jnp.asarray(a))
            tensors.append(None)
        elif isinstance(a, (list, tuple)):
            for x in a:
                walk(x)

    for a in args:
        walk(a)
    for k in sorted(kwargs):
        walk(kwargs[k])
    return arrays, tensors


def _flatten_args(args, kwargs):
    return _flatten_pairs(args, kwargs)[0]


def _flatten_arg_tensors(args, kwargs):
    """Tensor OBJECTS aligned with _flatten_args (None for non-Tensor
    leaves) — the grad-aware call path needs them as vjp targets."""
    return _flatten_pairs(args, kwargs)[1]


def _unflatten_args(tree_args, tree_kwargs, flat):
    it = iter(flat)

    def build(node):
        tag = node[0]
        if tag in ("leaf", "leaf_np"):
            return Tensor(next(it))
        if tag == "seq":
            seq = [build(x) for x in node[2]]
            return tuple(seq) if node[1] == "tuple" else seq
        return node[1]

    args = [build(n) for n in tree_args]
    kwargs = {}
    for k in sorted(tree_kwargs):
        kwargs[k] = build(tree_kwargs[k])
    return args, kwargs


def _flatten_out(out):
    flat, tree = [], None

    def conv(o):
        if isinstance(o, Tensor):
            flat.append(o)
            return ("leaf", len(flat) - 1)
        if isinstance(o, (list, tuple)):
            return ("seq", type(o).__name__, [conv(x) for x in o])
        if isinstance(o, dict):
            return ("dict", {k: conv(v) for k, v in o.items()})
        return ("const", o)

    tree = conv(out)
    return flat, tree


def _unflatten_tree(tree, tensors):
    def build(node):
        tag = node[0]
        if tag == "leaf":
            return tensors[node[1]]
        if tag == "seq":
            seq = [build(x) for x in node[2]]
            return tuple(seq) if node[1] == "tuple" else seq
        if tag == "dict":
            return {k: build(v) for k, v in node[1].items()}
        return node[1]

    return build(tree)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, check=False, **kwargs):
    """Decorator/wrapper: compile a function or a Layer's forward with XLA.

    check=True runs the trace-safety linter (paddle_tpu.analysis.check)
    over the function at DECORATION time and emits each finding as a
    TraceSafetyWarning — hazards surface before the first trace."""

    def _run_check(fn):
        import warnings

        from ..analysis import check as _lint_check
        from ..analysis.diagnostics import TraceSafetyWarning

        try:
            diags = _lint_check(fn)
        except TypeError:
            return
        for d in diags:
            warnings.warn(d.format(), TraceSafetyWarning, stacklevel=4)

    def decorate(obj):
        if isinstance(obj, Layer):
            if check:
                _run_check(obj.forward)
            static = StaticFunction(obj.forward, layer=obj,
                                    input_spec=input_spec,
                                    full_graph=full_graph)
            obj.forward = static
            return obj
        if check:
            _run_check(obj)
        return StaticFunction(obj, layer=None, input_spec=input_spec,
                              full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


def enable_to_static(flag):
    pass


# ---------------------------------------------------------------- save/load
def save(layer, path, input_spec=None, **configs):
    """jit.save parity: weights (.pdiparams analog) + a serialized StableHLO
    inference function via jax.export (.pdmodel analog)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..framework.io import save as fsave

    if isinstance(layer, Layer):
        fsave(layer.state_dict(), path + ".pdiparams")
        if input_spec:
            sd = layer.state_dict()
            names = list(sd.keys())
            # the export trace must see the dy2static-CONVERTED forward
            # (early exits / staged control flow), exactly like __call__
            # through to_static does — shadow the bound forward for the
            # duration of the export (hooks still run via layer(...))
            from .dy2static import convert_to_static

            orig_fwd = layer.forward
            conv_fwd = convert_to_static(orig_fwd)

            def infer_fn(state_arrays, *arg_arrays):
                arrays = dict(zip(names, state_arrays))
                with _tape.no_grad():
                    with layer.use_state(arrays):
                        out = layer(*[Tensor(a) for a in arg_arrays])
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._data for o in outs)

            in_names = [getattr(sp, "name", None) or f"input_{i}"
                        for i, sp in enumerate(input_spec)]
            if len(set(in_names)) != len(in_names):
                raise ValueError(
                    f"jit.save: input_spec names must be unique, got "
                    f"{in_names}")
            state_arrays = [sd[k]._data for k in names]
            # restore EXACTLY the prior instance state: a user's own
            # instance-level forward (monkey-patch, to_static wrapper)
            # must survive the export shadow
            had_inst = "forward" in layer.__dict__
            prev_inst = layer.__dict__.get("forward")
            if conv_fwd is not orig_fwd:
                object.__setattr__(layer, "forward", conv_fwd)
            try:
                exported = export_with_dynamic_dims(
                    jax.jit(infer_fn), [state_arrays],
                    [(tuple(spec.shape), spec.dtype)
                     for spec in input_spec])
            finally:
                if conv_fwd is not orig_fwd:
                    if had_inst:
                        object.__setattr__(layer, "forward", prev_inst)
                    else:
                        object.__delattr__(layer, "forward")
            write_artifact(
                path, exported,
                [(list(s.shape),
                  str(np.dtype(s.dtype) if s.dtype != jnp.bfloat16
                      else "bfloat16")) for s in input_spec],
                in_names, names)
    else:
        raise TypeError("jit.save expects a Layer")


def export_with_dynamic_dims(jit_fn, leading_args, specs):
    """jax.export with dynamic (None/-1) spec dims as SYMBOLIC dims so the
    served program accepts any size there (batch polymorphism). Shared by
    jit.save and static.save_inference_model. specs: [(shape, dtype)]
    where shape entries are int | None | -1. Symbols start fully
    independent; if shape-polymorphic tracing cannot relate them (e.g.
    two inputs whose batch dims must be equal: a + b), retry with ONE
    symbol per axis index — the common shared-batch contract."""
    def build(share_by_axis):
        sym = {}
        example, dynamic = [], False
        for shape, dtype in specs:
            dims = []
            for ax, s in enumerate(shape):
                if s is None or (isinstance(s, int) and s < 0):
                    dynamic = True
                    key = ax if share_by_axis else len(sym)
                    if key not in sym:
                        (sym[key],) = jax.export.symbolic_shape(
                            f"d{len(sym)}")
                    dims.append(sym[key])
                else:
                    dims.append(int(s))
            example.append(jax.ShapeDtypeStruct(tuple(dims), dtype))
        return example, dynamic

    example, dynamic = build(False)
    if not dynamic:
        concrete = [jnp.zeros(tuple(s.shape), s.dtype) for s in example]
        return jax.export.export(jit_fn)(*leading_args, *concrete)
    try:
        return jax.export.export(jit_fn)(*leading_args, *example)
    except Exception:
        example, _ = build(True)
        return jax.export.export(jit_fn)(*leading_args, *example)


def write_artifact(path, exported, input_spec, input_names, state_names,
                   output_names=None):
    """The ONE .pdmodel blob schema — shared by jit.save and
    static.save_inference_model so jit.load / inference.Predictor never
    see divergent producers. Output metadata (names + avals) is persisted
    so the Predictor exposes REAL fetch names instead of fabricating
    output_{i} (VERDICT r3 item 7)."""
    n_out = len(exported.out_avals)
    if output_names is None:
        output_names = [f"output_{i}" for i in range(n_out)]
    if len(output_names) != n_out:
        raise ValueError(
            f"write_artifact: {len(output_names)} output names for "
            f"{n_out} exported outputs")
    if len(set(output_names)) != len(output_names):
        raise ValueError(
            f"write_artifact: duplicate output names {output_names}")
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump({
            "stablehlo": exported.serialize(),
            "input_spec": input_spec,
            "input_names": input_names,
            "state_names": state_names,
            "output_names": list(output_names),
            # symbolic (batch-polymorphic) dims pickle as -1
            "output_spec": [([d if isinstance(d, int) else -1
                              for d in a.shape], str(a.dtype))
                            for a in exported.out_avals],
        }, f)


class TranslatedLayer(Layer):
    """jit.load result: runs the deserialized StableHLO program."""

    def __init__(self, exported, state_arrays, input_spec=None,
                 input_names=None, output_names=None):
        super().__init__()
        self._exported = exported
        self._state_arrays = state_arrays
        self._input_spec = input_spec or []
        self._input_names = input_names or [
            f"input_{i}" for i in range(len(self._input_spec))]
        self._output_names = output_names or [
            f"output_{i}" for i in range(len(exported.out_avals))]

    def forward(self, *args):
        arrs = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        outs = self._exported.call(self._state_arrays, *arrs)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        blob = pickle.load(f)
    exported = jax.export.deserialize(blob["stablehlo"])
    from ..framework.io import load as fload

    sd = fload(configs.get("params_path") or path + ".pdiparams")
    state_arrays = [sd[k]._data for k in blob["state_names"]]
    return TranslatedLayer(exported, state_arrays,
                           input_spec=blob.get("input_spec"),
                           input_names=blob.get("input_names"),
                           output_names=blob.get("output_names"))
