"""paddle.vision.datasets parity. Zero-egress build: the download-backed
datasets (MNIST/Cifar/Flowers) accept a local `data_file`; FakeData generates
synthetic samples for pipelines and benchmarks."""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic image classification dataset (benchmark feeder)."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.rng = np.random.RandomState(seed)
        self._images = self.rng.rand(min(size, 64), *self.image_shape).astype(np.float32)
        self._labels = self.rng.randint(0, num_classes, size=size).astype(np.int32)

    def __getitem__(self, idx):
        img = self._images[idx % len(self._images)]
        if self.transform:
            img = self.transform(img)
        return img, self._labels[idx]

    def __len__(self):
        return self.size


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if image_path is None or not os.path.exists(image_path):
            raise FileNotFoundError(
                "MNIST requires local idx files (zero-egress build): pass "
                "image_path/label_path explicitly"
            )
        self.transform = transform
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") else open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") else open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int32)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                "Cifar10 requires a local pickle batch file (zero-egress build)"
            )
        with open(data_file, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        self.images = batch[b"data"].reshape(-1, 3, 32, 32)
        self.labels = np.asarray(batch[b"labels"], np.int32)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """Image-folder dataset; requires an image decoder (PIL unavailable in the
    base image — arrays saved as .npy are supported natively)."""

    def __init__(self, root, loader=None, extensions=(".npy",), transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fn in sorted(os.listdir(d)):
                if fn.endswith(tuple(extensions)):
                    self.samples.append((os.path.join(d, fn), self.class_to_idx[c]))
        self.loader = loader or (lambda p: np.load(p))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder
