"""paddle.vision.ops parity (ref: python/paddle/vision/ops.py (U) backed by
CUDA kernels in paddle/fluid/operators/detection/ — SURVEY.md §2.1 N27).

TPU-native design: everything is static-shape. NMS runs the greedy suppress
loop as `lax.fori_loop` over a fixed box budget (XLA-friendly; no dynamic
output — callers slice by the returned count or use the padded index array).
roi_align is a gather + bilinear interpolation, vectorized over sampling
points so it lowers to batched gathers on the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..tensor.creation import _as_t


def box_iou(boxes1, boxes2):
    """Pairwise IoU [N, M] for boxes in xyxy."""

    def f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.maximum(rb - lt, 0.0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-10)

    return apply(f, _as_t(boxes1), _as_t(boxes2), _op_name="box_iou")


@functools.partial(jax.jit, static_argnums=(2,))
def _nms_core(boxes, scores, iou_threshold):
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    boxes_sorted = boxes[order]
    area = ((boxes_sorted[:, 2] - boxes_sorted[:, 0])
            * (boxes_sorted[:, 3] - boxes_sorted[:, 1]))

    def body(i, keep):
        # suppress every later box overlapping box i (if i itself is kept)
        lt = jnp.maximum(boxes_sorted[i, :2], boxes_sorted[:, :2])
        rb = jnp.minimum(boxes_sorted[i, 2:], boxes_sorted[:, 2:])
        wh = jnp.maximum(rb - lt, 0.0)
        inter = wh[:, 0] * wh[:, 1]
        iou = inter / jnp.maximum(area[i] + area - inter, 1e-10)
        later = jnp.arange(n) > i
        suppress = later & (iou > iou_threshold)
        return jnp.where(keep[i], keep & ~suppress, keep)

    keep = lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    return order, keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS; returns kept box indices sorted by score (ref nms).
    With `categories`, NMS is applied per category (batched-class trick:
    offset boxes by category so cross-class boxes never overlap)."""
    b = _as_t(boxes)._data
    n = b.shape[0]
    s = (_as_t(scores)._data if scores is not None
         else jnp.arange(n, 0, -1, dtype=jnp.float32))
    if category_idxs is not None:
        cidx = _as_t(category_idxs)._data
        offset = (cidx.astype(b.dtype) * (b.max() + 1.0))[:, None]
        b = b + offset
    order, keep = _nms_core(b, s, float(iou_threshold))
    import numpy as np

    order_np = np.asarray(order)
    keep_np = np.asarray(keep)
    # keep is in score-sorted order; map back to original box indices
    kept = order_np[np.nonzero(keep_np)[0]]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int32))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (ref roi_align): x [N,C,H,W], boxes [R,4] xyxy in input
    coords, boxes_num [N] rois per image -> [R, C, out_h, out_w].

    Deviation from the reference: sampling_ratio=-1 uses a FIXED 2 samples
    per bin per axis instead of the reference's adaptive
    ceil(roi_size/out_size) — adaptive counts are data-dependent and cannot
    be expressed with XLA static shapes. Pass an explicit sampling_ratio for
    closer numerical parity on large RoIs."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out_h, out_w = output_size
    xt = _as_t(x)
    bt = _as_t(boxes)
    bn = _as_t(boxes_num)

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        # map each roi to its image index
        img_idx = jnp.repeat(jnp.arange(n), rois_num, axis=0,
                             total_repeat_length=r)
        off = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - off
        y1 = rois[:, 1] * spatial_scale - off
        x2 = rois[:, 2] * spatial_scale - off
        y2 = rois[:, 3] * spatial_scale - off
        roi_w = x2 - x1
        roi_h = y2 - y1
        if not aligned:
            roi_w = jnp.maximum(roi_w, 1.0)
            roi_h = jnp.maximum(roi_h, 1.0)
        bin_w = roi_w / out_w
        bin_h = roi_h / out_h
        ns = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [R, out, ns] center offsets per bin
        iy = (jnp.arange(ns) + 0.5) / ns
        ys = (y1[:, None, None]
              + (jnp.arange(out_h)[None, :, None] + iy[None, None, :])
              * bin_h[:, None, None])                     # [R, out_h, ns]
        xs = (x1[:, None, None]
              + (jnp.arange(out_w)[None, :, None] + iy[None, None, :])
              * bin_w[:, None, None])                     # [R, out_w, ns]

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy/xx [...] -> [C, ...]
            yy = jnp.clip(yy, 0.0, h - 1.0)
            xx = jnp.clip(xx, 0.0, w - 1.0)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, h - 1)
            x1_ = jnp.minimum(x0 + 1, w - 1)
            wy = yy - y0
            wx = xx - x0
            v00 = img[:, y0, x0]
            v01 = img[:, y0, x1_]
            v10 = img[:, y1_, x0]
            v11 = img[:, y1_, x1_]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)

        def per_roi(ri):
            img = feat[img_idx[ri]]
            yy = ys[ri]  # [out_h, ns]
            xx = xs[ri]  # [out_w, ns]
            # full grid [out_h, ns, out_w, ns]
            ygrid = yy[:, :, None, None]
            xgrid = xx[None, None, :, :]
            vals = bilinear(img, jnp.broadcast_to(ygrid, (out_h, ns, out_w, ns)),
                            jnp.broadcast_to(xgrid, (out_h, ns, out_w, ns)))
            return vals.reshape(c, out_h, ns, out_w, ns).mean(axis=(2, 4))

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply(f, xt, bt, bn, _op_name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (max pooling variant) via roi_align sampling at high density
    with max reduction approximated by dense align — exact max pooling over
    quantized bins, matching the reference op."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out_h, out_w = output_size
    xt = _as_t(x)
    bt = _as_t(boxes)
    bn = _as_t(boxes_num)

    def f(feat, rois, rois_num):
        n, c, h, w = feat.shape
        r = rois.shape[0]
        img_idx = jnp.repeat(jnp.arange(n), rois_num, axis=0,
                             total_repeat_length=r)
        x1 = jnp.round(rois[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(rois[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(rois[:, 2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(rois[:, 3] * spatial_scale).astype(jnp.int32)
        roi_w = jnp.maximum(x2 - x1 + 1, 1)
        roi_h = jnp.maximum(y2 - y1 + 1, 1)

        ys_all = jnp.arange(h)
        xs_all = jnp.arange(w)
        big_neg = jnp.asarray(-3.4e38, feat.dtype)

        def bin_masks(rel, roi_len, n_bins):
            """[n_bins, size] membership with the reference's overlapping
            floor/ceil boundaries: bin i covers
            [floor(i·L/n), ceil((i+1)·L/n))."""
            i = jnp.arange(n_bins, dtype=jnp.float32)[:, None]
            start = jnp.floor(i * roi_len / n_bins)
            end = jnp.ceil((i + 1) * roi_len / n_bins)
            within = (rel[None, :] >= start) & (rel[None, :] < end)
            valid = (rel >= 0) & (rel < roi_len)
            return within & valid[None, :]

        def per_roi(ri):
            img = feat[img_idx[ri]]  # [C, H, W]
            ymask = bin_masks(ys_all - y1[ri], roi_h[ri], out_h)
            xmask = bin_masks(xs_all - x1[ri], roi_w[ri], out_w)
            # two-stage max keeps the transient at [C, H, out_w]
            col = jnp.stack(
                [jnp.max(jnp.where(xmask[j][None, None, :], img, big_neg),
                         axis=2) for j in range(out_w)], axis=-1)
            pooled = jnp.stack(
                [jnp.max(jnp.where(ymask[i][None, :, None], col, big_neg),
                         axis=1) for i in range(out_h)], axis=1)
            any_px = (ymask.any(axis=1)[:, None] & xmask.any(axis=1)[None, :])
            return jnp.where(any_px[None], pooled, 0.0)

        return jax.vmap(per_roi)(jnp.arange(r))

    return apply(f, xt, bt, bn, _op_name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (ref box_coder op)."""
    if axis != 0:
        raise NotImplementedError("box_coder axis=1 layout not supported")
    pb = _as_t(prior_box)._data
    pbv = _as_t(prior_box_var)._data if prior_box_var is not None else None
    if pbv is not None and pbv.ndim == 1:
        # a single 4-vector of variances applies to every prior
        pbv = jnp.broadcast_to(pbv[None, :], (pb.shape[0], 4))
    tb = _as_t(target_box)._data
    norm = 0.0 if box_normalized else 1.0

    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5

    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pbv is not None:
            out = out / pbv[None, :, :]
        return Tensor(out)
    elif code_type == "decode_center_size":
        d = tb  # [N, M, 4] or [M, 4]
        if d.ndim == 2:
            d = d[None]
        if pbv is not None:
            d = d * pbv[None, :, :]
        dcx = d[..., 0] * pw + pcx
        dcy = d[..., 1] * ph + pcy
        dw = jnp.exp(d[..., 2]) * pw
        dh = jnp.exp(d[..., 3]) * ph
        out = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                         dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm],
                        axis=-1)
        return Tensor(out[0] if tb.ndim == 2 else out)
    raise ValueError(f"unknown code_type {code_type}")


__all__ = ["box_iou", "nms", "roi_align", "roi_pool", "box_coder"]
