from . import models
from . import transforms
from . import datasets
from . import ops
from .models import *  # noqa: F401,F403


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"


def image_load(path, backend=None):
    """Load an image file to an ndarray (zero-egress build: PIL if present,
    else raw numpy .npy; the reference defaults to PIL/cv2)."""
    import numpy as np

    if str(path).endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return Image.open(path)
    except ImportError as e:
        raise RuntimeError(
            "image_load needs PIL (not in this build) for non-.npy files"
        ) from e
