from . import models
from . import transforms
from . import datasets
from . import ops
from .models import *  # noqa: F401,F403


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"
