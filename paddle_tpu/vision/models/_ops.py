"""Shared building blocks for the vision model zoo."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import Conv2D, BatchNorm2D, ReLU


def make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(Layer):
    def __init__(self, in_ch, out_ch, kernel, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=padding, groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(out_ch)
        self.relu = ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))
