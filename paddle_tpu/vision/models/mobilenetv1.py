"""MobileNetV1 (ref: python/paddle/vision/models/mobilenetv1.py (U))."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import AdaptiveAvgPool2D, Linear, Sequential
from ...tensor.manipulation import flatten
from ._ops import ConvBNReLU as _ConvBNReLU


class _DepthwiseSeparable(Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.depthwise = _ConvBNReLU(in_ch, in_ch, 3, stride=stride,
                                     padding=1, groups=in_ch)
        self.pointwise = _ConvBNReLU(in_ch, out_ch, 1)

    def forward(self, x):
        return self.pointwise(self.depthwise(x))


class MobileNetV1(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [  # (out, stride)
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
            (1024, 2), (1024, 1),
        ]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2, padding=1)]
        in_ch = c(32)
        for out, stride in cfg:
            layers.append(_DepthwiseSeparable(in_ch, c(out), stride))
            in_ch = c(out)
        self.features = Sequential(*layers)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return MobileNetV1(scale=scale, **kwargs)
