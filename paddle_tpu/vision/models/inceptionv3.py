"""Inception-v3 (ref: python/paddle/vision/models/inceptionv3.py (U))."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import (
    MaxPool2D, AvgPool2D, AdaptiveAvgPool2D, Linear, Dropout, Sequential,
)
from ...tensor.manipulation import concat, flatten
from ._ops import ConvBNReLU


class InceptionA(Layer):
    def __init__(self, in_ch, pool_features):
        super().__init__()
        self.b1x1 = ConvBNReLU(in_ch, 64, 1)
        self.b5x5 = Sequential(ConvBNReLU(in_ch, 48, 1),
                               ConvBNReLU(48, 64, 5, padding=2))
        self.b3x3dbl = Sequential(ConvBNReLU(in_ch, 64, 1),
                                  ConvBNReLU(64, 96, 3, padding=1),
                                  ConvBNReLU(96, 96, 3, padding=1))
        self.bpool = Sequential(AvgPool2D(kernel_size=3, stride=1, padding=1),
                                ConvBNReLU(in_ch, pool_features, 1))

    def forward(self, x):
        return concat([self.b1x1(x), self.b5x5(x), self.b3x3dbl(x),
                       self.bpool(x)], axis=1)


class InceptionB(Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3x3 = ConvBNReLU(in_ch, 384, 3, stride=2)
        self.b3x3dbl = Sequential(ConvBNReLU(in_ch, 64, 1),
                                  ConvBNReLU(64, 96, 3, padding=1),
                                  ConvBNReLU(96, 96, 3, stride=2))
        self.pool = MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return concat([self.b3x3(x), self.b3x3dbl(x), self.pool(x)], axis=1)


class InceptionC(Layer):
    def __init__(self, in_ch, ch7):
        super().__init__()
        self.b1x1 = ConvBNReLU(in_ch, 192, 1)
        self.b7x7 = Sequential(
            ConvBNReLU(in_ch, ch7, 1),
            ConvBNReLU(ch7, ch7, (1, 7), padding=(0, 3)),
            ConvBNReLU(ch7, 192, (7, 1), padding=(3, 0)),
        )
        self.b7x7dbl = Sequential(
            ConvBNReLU(in_ch, ch7, 1),
            ConvBNReLU(ch7, ch7, (7, 1), padding=(3, 0)),
            ConvBNReLU(ch7, ch7, (1, 7), padding=(0, 3)),
            ConvBNReLU(ch7, ch7, (7, 1), padding=(3, 0)),
            ConvBNReLU(ch7, 192, (1, 7), padding=(0, 3)),
        )
        self.bpool = Sequential(AvgPool2D(kernel_size=3, stride=1, padding=1),
                                ConvBNReLU(in_ch, 192, 1))

    def forward(self, x):
        return concat([self.b1x1(x), self.b7x7(x), self.b7x7dbl(x),
                       self.bpool(x)], axis=1)


class InceptionD(Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3x3 = Sequential(ConvBNReLU(in_ch, 192, 1),
                               ConvBNReLU(192, 320, 3, stride=2))
        self.b7x7x3 = Sequential(
            ConvBNReLU(in_ch, 192, 1),
            ConvBNReLU(192, 192, (1, 7), padding=(0, 3)),
            ConvBNReLU(192, 192, (7, 1), padding=(3, 0)),
            ConvBNReLU(192, 192, 3, stride=2),
        )
        self.pool = MaxPool2D(kernel_size=3, stride=2)

    def forward(self, x):
        return concat([self.b3x3(x), self.b7x7x3(x), self.pool(x)], axis=1)


class InceptionE(Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b1x1 = ConvBNReLU(in_ch, 320, 1)
        self.b3x3_1 = ConvBNReLU(in_ch, 384, 1)
        self.b3x3_2a = ConvBNReLU(384, 384, (1, 3), padding=(0, 1))
        self.b3x3_2b = ConvBNReLU(384, 384, (3, 1), padding=(1, 0))
        self.b3x3dbl_1 = Sequential(ConvBNReLU(in_ch, 448, 1),
                                    ConvBNReLU(448, 384, 3, padding=1))
        self.b3x3dbl_2a = ConvBNReLU(384, 384, (1, 3), padding=(0, 1))
        self.b3x3dbl_2b = ConvBNReLU(384, 384, (3, 1), padding=(1, 0))
        self.bpool = Sequential(AvgPool2D(kernel_size=3, stride=1, padding=1),
                                ConvBNReLU(in_ch, 192, 1))

    def forward(self, x):
        b3 = self.b3x3_1(x)
        b3 = concat([self.b3x3_2a(b3), self.b3x3_2b(b3)], axis=1)
        bd = self.b3x3dbl_1(x)
        bd = concat([self.b3x3dbl_2a(bd), self.b3x3dbl_2b(bd)], axis=1)
        return concat([self.b1x1(x), b3, bd, self.bpool(x)], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            ConvBNReLU(3, 32, 3, stride=2),
            ConvBNReLU(32, 32, 3),
            ConvBNReLU(32, 64, 3, padding=1),
            MaxPool2D(kernel_size=3, stride=2),
            ConvBNReLU(64, 80, 1),
            ConvBNReLU(80, 192, 3),
            MaxPool2D(kernel_size=3, stride=2),
        )
        self.blocks = Sequential(
            InceptionA(192, 32), InceptionA(256, 64), InceptionA(288, 64),
            InceptionB(288),
            InceptionC(768, 128), InceptionC(768, 160), InceptionC(768, 160),
            InceptionC(768, 192),
            InceptionD(768),
            InceptionE(1280), InceptionE(2048),
        )
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = Dropout(0.5)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return InceptionV3(**kwargs)
