"""GoogLeNet / Inception-v1 (ref: python/paddle/vision/models/googlenet.py
(U)). Aux classifiers are built but only used in training mode, matching
the reference's (out, aux1, aux2) return convention."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import (
    MaxPool2D, AdaptiveAvgPool2D, AvgPool2D, Linear, Dropout, Sequential,
    ReLU,
)
from ...tensor.manipulation import concat, flatten
from ._ops import ConvBNReLU


class Inception(Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.branch1 = ConvBNReLU(in_ch, c1, 1)
        self.branch2 = Sequential(ConvBNReLU(in_ch, c3r, 1),
                                  ConvBNReLU(c3r, c3, 3, padding=1))
        self.branch3 = Sequential(ConvBNReLU(in_ch, c5r, 1),
                                  ConvBNReLU(c5r, c5, 5, padding=2))
        self.branch4 = Sequential(MaxPool2D(kernel_size=3, stride=1, padding=1),
                                  ConvBNReLU(in_ch, proj, 1))

    def forward(self, x):
        return concat([self.branch1(x), self.branch2(x), self.branch3(x),
                       self.branch4(x)], axis=1)


class InceptionAux(Layer):
    def __init__(self, in_ch, num_classes):
        super().__init__()
        self.avgpool = AvgPool2D(kernel_size=5, stride=3)
        self.conv = ConvBNReLU(in_ch, 128, 1)
        self.fc1 = Linear(2048, 1024)
        self.relu = ReLU()
        self.dropout = Dropout(0.7)
        self.fc2 = Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.avgpool(x))
        x = flatten(x, 1)
        x = self.dropout(self.relu(self.fc1(x)))
        return self.fc2(x)


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = ConvBNReLU(3, 64, 7, stride=2, padding=3)
        self.pool1 = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.conv2 = ConvBNReLU(64, 64, 1)
        self.conv3 = ConvBNReLU(64, 192, 3, padding=1)
        self.pool2 = MaxPool2D(kernel_size=3, stride=2, padding=1)

        self.ince3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.ince3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.ince4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.ince4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.ince4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.ince4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.ince4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.ince5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.ince5b = Inception(832, 384, 192, 384, 48, 128, 128)

        if num_classes > 0:
            self.aux1 = InceptionAux(512, num_classes)
            self.aux2 = InceptionAux(528, num_classes)
            self.dropout = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.pool2(self.conv3(self.conv2(x)))
        x = self.ince3b(self.ince3a(x))
        x = self.pool3(x)
        x = self.ince4a(x)
        aux1 = self.aux1(x) if self.training and self.num_classes > 0 else None
        x = self.ince4d(self.ince4c(self.ince4b(x)))
        aux2 = self.aux2(x) if self.training and self.num_classes > 0 else None
        x = self.pool4(self.ince4e(x))
        x = self.ince5b(self.ince5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return (x, aux1, aux2) if self.training and self.num_classes > 0 else x


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return GoogLeNet(**kwargs)
