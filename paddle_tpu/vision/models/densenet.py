"""DenseNet (ref: python/paddle/vision/models/densenet.py (U) — same growth
rates / block configs; fresh init, no pretrained download)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import (
    Conv2D, BatchNorm2D, ReLU, MaxPool2D, AvgPool2D, AdaptiveAvgPool2D,
    Linear, Dropout, Sequential,
)
from ...tensor.manipulation import concat, flatten


class _DenseLayer(Layer):
    def __init__(self, num_input_features, growth_rate, bn_size, drop_rate):
        super().__init__()
        self.norm1 = BatchNorm2D(num_input_features)
        self.relu = ReLU()
        self.conv1 = Conv2D(num_input_features, bn_size * growth_rate, 1,
                            bias_attr=False)
        self.norm2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                            bias_attr=False)
        self.dropout = Dropout(drop_rate)

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        out = self.dropout(out)
        return concat([x, out], axis=1)


class _DenseBlock(Layer):
    def __init__(self, num_layers, num_input_features, bn_size, growth_rate,
                 drop_rate):
        super().__init__()
        layers = []
        for i in range(num_layers):
            layers.append(_DenseLayer(num_input_features + i * growth_rate,
                                      growth_rate, bn_size, drop_rate))
        self.block = Sequential(*layers)

    def forward(self, x):
        return self.block(x)


class _Transition(Layer):
    def __init__(self, num_input_features, num_output_features):
        super().__init__()
        self.norm = BatchNorm2D(num_input_features)
        self.relu = ReLU()
        self.conv = Conv2D(num_input_features, num_output_features, 1,
                           bias_attr=False)
        self.pool = AvgPool2D(kernel_size=2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


_CFG = {
    121: (32, (6, 12, 24, 16)),
    161: (48, (6, 12, 36, 24)),
    169: (32, (6, 12, 32, 32)),
    201: (32, (6, 12, 48, 32)),
    264: (32, (6, 12, 64, 48)),
}


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        growth_rate, block_config = _CFG[layers]
        num_init_features = 2 * growth_rate
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv0 = Conv2D(3, num_init_features, 7, stride=2, padding=3,
                            bias_attr=False)
        self.norm0 = BatchNorm2D(num_init_features)
        self.relu = ReLU()
        self.pool0 = MaxPool2D(kernel_size=3, stride=2, padding=1)

        blocks = []
        num_features = num_init_features
        for i, num_layers in enumerate(block_config):
            blocks.append(_DenseBlock(num_layers, num_features, bn_size,
                                      growth_rate, dropout))
            num_features += num_layers * growth_rate
            if i != len(block_config) - 1:
                blocks.append(_Transition(num_features, num_features // 2))
                num_features //= 2
        self.features = Sequential(*blocks)
        self.norm5 = BatchNorm2D(num_features)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Linear(num_features, num_classes)

    def forward(self, x):
        x = self.pool0(self.relu(self.norm0(self.conv0(x))))
        x = self.relu(self.norm5(self.features(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
