"""SqueezeNet (ref: python/paddle/vision/models/squeezenet.py (U))."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import (
    Conv2D, ReLU, MaxPool2D, AdaptiveAvgPool2D, Dropout, Sequential,
)
from ...tensor.manipulation import concat, flatten


class Fire(Layer):
    def __init__(self, inplanes, squeeze_planes, expand1x1_planes,
                 expand3x3_planes):
        super().__init__()
        self.squeeze = Conv2D(inplanes, squeeze_planes, 1)
        self.relu = ReLU()
        self.expand1x1 = Conv2D(squeeze_planes, expand1x1_planes, 1)
        self.expand3x3 = Conv2D(squeeze_planes, expand3x3_planes, 3, padding=1)

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        return concat([self.relu(self.expand1x1(x)),
                       self.relu(self.expand3x3(x))], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.1", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(kernel_size=3, stride=2),
                Fire(96, 16, 64, 64), Fire(128, 16, 64, 64),
                Fire(128, 32, 128, 128),
                MaxPool2D(kernel_size=3, stride=2),
                Fire(256, 32, 128, 128), Fire(256, 48, 192, 192),
                Fire(384, 48, 192, 192), Fire(384, 64, 256, 256),
                MaxPool2D(kernel_size=3, stride=2),
                Fire(512, 64, 256, 256),
            )
        elif version == "1.1":
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(),
                MaxPool2D(kernel_size=3, stride=2),
                Fire(64, 16, 64, 64), Fire(128, 16, 64, 64),
                MaxPool2D(kernel_size=3, stride=2),
                Fire(128, 32, 128, 128), Fire(256, 32, 128, 128),
                MaxPool2D(kernel_size=3, stride=2),
                Fire(256, 48, 192, 192), Fire(384, 48, 192, 192),
                Fire(384, 64, 256, 256), Fire(512, 64, 256, 256),
            )
        else:
            raise ValueError(f"unsupported SqueezeNet version {version}")
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5),
                Conv2D(512, num_classes, 1),
                ReLU(),
            )
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.avgpool(x)
        return flatten(x, 1)


def _squeezenet(version, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return SqueezeNet(version, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
