"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py (U)).

channel_shuffle is a reshape/transpose pair — free on TPU (XLA folds it
into the surrounding convolution layouts)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import (
    Conv2D, BatchNorm2D, ReLU, MaxPool2D, AdaptiveAvgPool2D, Linear,
    Sequential,
)
from ...tensor.manipulation import concat, flatten, reshape, transpose


def channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


def _split(x):
    c = x.shape[1] // 2
    return x[:, :c], x[:, c:]


class InvertedResidual(Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.stride = stride
        branch_ch = out_ch // 2
        if stride == 1:
            self.branch2 = Sequential(
                Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
                BatchNorm2D(branch_ch), ReLU(),
                Conv2D(branch_ch, branch_ch, 3, stride=1, padding=1,
                       groups=branch_ch, bias_attr=False),
                BatchNorm2D(branch_ch),
                Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
                BatchNorm2D(branch_ch), ReLU(),
            )
        else:
            self.branch1 = Sequential(
                Conv2D(in_ch, in_ch, 3, stride=stride, padding=1,
                       groups=in_ch, bias_attr=False),
                BatchNorm2D(in_ch),
                Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                BatchNorm2D(branch_ch), ReLU(),
            )
            self.branch2 = Sequential(
                Conv2D(in_ch, branch_ch, 1, bias_attr=False),
                BatchNorm2D(branch_ch), ReLU(),
                Conv2D(branch_ch, branch_ch, 3, stride=stride, padding=1,
                       groups=branch_ch, bias_attr=False),
                BatchNorm2D(branch_ch),
                Conv2D(branch_ch, branch_ch, 1, bias_attr=False),
                BatchNorm2D(branch_ch), ReLU(),
            )

    def forward(self, x):
        if self.stride == 1:
            x1, x2 = _split(x)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


_STAGE_OUT = {
    "0.25x": (24, 48, 96, 192, 1024),
    "0.33x": (24, 32, 64, 128, 1024),
    "0.5x": (24, 48, 96, 192, 1024),
    "1.0x": (24, 116, 232, 464, 1024),
    "1.5x": (24, 176, 352, 704, 1024),
    "2.0x": (24, 244, 488, 976, 2048),
}
_STAGE_REPEATS = (4, 8, 4)


class ShuffleNetV2(Layer):
    def __init__(self, scale="1.0x", act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if act != "relu":
            raise NotImplementedError(
                f"ShuffleNetV2 act={act!r} not supported (relu only)")
        self.num_classes = num_classes
        self.with_pool = with_pool
        chs = _STAGE_OUT[scale]

        self.conv1 = Sequential(
            Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            BatchNorm2D(chs[0]), ReLU(),
        )
        self.maxpool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        stages = []
        in_ch = chs[0]
        for i, repeats in enumerate(_STAGE_REPEATS):
            out_ch = chs[i + 1]
            blocks = [InvertedResidual(in_ch, out_ch, stride=2)]
            for _ in range(repeats - 1):
                blocks.append(InvertedResidual(out_ch, out_ch, stride=1))
            stages.append(Sequential(*blocks))
            in_ch = out_ch
        self.stages = Sequential(*stages)
        self.conv5 = Sequential(
            Conv2D(in_ch, chs[-1], 1, bias_attr=False),
            BatchNorm2D(chs[-1]), ReLU(),
        )
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = Linear(chs[-1], num_classes)

    def forward(self, x):
        x = self.maxpool(self.conv1(x))
        x = self.stages(x)
        x = self.conv5(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _shufflenet(scale, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet("0.25x", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet("0.33x", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet("0.5x", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet("1.0x", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet("1.5x", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet("2.0x", pretrained, **kwargs)
