"""MobileNetV3 small/large (ref: python/paddle/vision/models/mobilenetv3.py
(U) — same bneck configs with squeeze-excite and hardswish)."""

from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer import (
    Conv2D, BatchNorm2D, ReLU, Hardswish, Hardsigmoid, AdaptiveAvgPool2D,
    Linear, Dropout, Sequential,
)
from ...tensor.manipulation import flatten
from ._ops import make_divisible as _make_divisible


class _ConvBNAct(Layer):
    def __init__(self, in_ch, out_ch, kernel, stride=1, groups=1, act=None):
        super().__init__()
        self.conv = Conv2D(in_ch, out_ch, kernel, stride=stride,
                           padding=kernel // 2, groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(out_ch)
        self.act = act() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


class SqueezeExcite(Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        squeeze = _make_divisible(ch // reduction)
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.fc1 = Conv2D(ch, squeeze, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(squeeze, ch, 1)
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.avgpool(x)
        s = self.hsig(self.fc2(self.relu(self.fc1(s))))
        return x * s


class _Bneck(Layer):
    def __init__(self, in_ch, exp, out_ch, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        if exp != in_ch:
            layers.append(_ConvBNAct(in_ch, exp, 1, act=act))
        layers.append(_ConvBNAct(exp, exp, kernel, stride=stride, groups=exp,
                                 act=act))
        if use_se:
            layers.append(SqueezeExcite(exp))
        layers.append(_ConvBNAct(exp, out_ch, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, SE, act, stride)
_LARGE = [
    (3, 16, 16, False, ReLU, 1),
    (3, 64, 24, False, ReLU, 2),
    (3, 72, 24, False, ReLU, 1),
    (5, 72, 40, True, ReLU, 2),
    (5, 120, 40, True, ReLU, 1),
    (5, 120, 40, True, ReLU, 1),
    (3, 240, 80, False, Hardswish, 2),
    (3, 200, 80, False, Hardswish, 1),
    (3, 184, 80, False, Hardswish, 1),
    (3, 184, 80, False, Hardswish, 1),
    (3, 480, 112, True, Hardswish, 1),
    (3, 672, 112, True, Hardswish, 1),
    (5, 672, 160, True, Hardswish, 2),
    (5, 960, 160, True, Hardswish, 1),
    (5, 960, 160, True, Hardswish, 1),
]
_SMALL = [
    (3, 16, 16, True, ReLU, 2),
    (3, 72, 24, False, ReLU, 2),
    (3, 88, 24, False, ReLU, 1),
    (5, 96, 40, True, Hardswish, 2),
    (5, 240, 40, True, Hardswish, 1),
    (5, 240, 40, True, Hardswish, 1),
    (5, 120, 48, True, Hardswish, 1),
    (5, 144, 48, True, Hardswish, 1),
    (5, 288, 96, True, Hardswish, 2),
    (5, 576, 96, True, Hardswish, 1),
    (5, 576, 96, True, Hardswish, 1),
]


class MobileNetV3(Layer):
    def __init__(self, cfg, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        layers = [_ConvBNAct(3, c(16), 3, stride=2, act=Hardswish)]
        in_ch = c(16)
        for kernel, exp, out, se, act, stride in cfg:
            layers.append(_Bneck(in_ch, c(exp), c(out), kernel, stride, se, act))
            in_ch = c(out)
        last_conv = c(cfg[-1][1])
        layers.append(_ConvBNAct(in_ch, last_conv, 1, act=Hardswish))
        self.features = Sequential(*layers)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_conv, last_channel), Hardswish(),
                Dropout(0.2), Linear(last_channel, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights require network access")
    return MobileNetV3Small(scale=scale, **kwargs)
