"""paddle.vision.transforms parity (numpy-array transforms; ref:
python/paddle/vision/transforms/ (U)). Images are HWC numpy arrays or CHW
Tensors; ToTensor converts HWC uint8 -> CHW float32/255."""

from __future__ import annotations

import random as pyrandom

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _as_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _as_np(img).astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax

        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        if chw:
            tgt = (arr.shape[0], th, tw)
        else:
            tgt = (th, tw) + ((arr.shape[2],) if arr.ndim == 3 else ())
        method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[self.interpolation]
        out = np.asarray(jax.image.resize(arr.astype(np.float32), tgt, method=method))
        if arr.dtype == np.uint8:
            out = out.clip(0, 255).astype(np.uint8)
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            if chw:
                arr = np.pad(arr, [(0, 0), (p[1], p[3]), (p[0], p[2])])
            else:
                pads = [(p[1], p[3]), (p[0], p[2])] + ([(0, 0)] if arr.ndim == 3 else [])
                arr = np.pad(arr, pads)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _as_np(img)
        if pyrandom.random() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
            return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _as_np(img)
        if pyrandom.random() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
            return arr[:, ::-1].copy() if chw else arr[::-1].copy()
        return arr


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        import math

        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            aspect = math.exp(pyrandom.uniform(math.log(self.ratio[0]), math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target_area * aspect)))
            ch = int(round(math.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                i = pyrandom.randint(0, h - ch)
                j = pyrandom.randint(0, w - cw)
                crop = arr[:, i:i + ch, j:j + cw] if chw else arr[i:i + ch, j:j + cw]
                return self._resize(crop)
        return self._resize(CenterCrop(min(h, w))(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _as_np(img).astype(np.float32)
        factor = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return (arr * factor).clip(0, 255).astype(np.uint8) if _as_np(img).dtype == np.uint8 else arr * factor


# functional API
def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = _as_np(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
    return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    arr = _as_np(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
    if chw:
        return arr[:, top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]


# ---------------------------------------------------------------- round 2
def _is_chw(arr):
    return (arr.ndim == 3 and arr.shape[0] in (1, 3)
            and arr.shape[0] < arr.shape[2])


def _to_hwc(arr):
    return (arr.transpose(1, 2, 0), True) if _is_chw(arr) else (arr, False)


def _from_hwc(arr, was_chw):
    return arr.transpose(2, 0, 1) if was_chw else arr


def vflip(img):
    arr = _as_np(img)
    chw = _is_chw(arr)
    return arr[:, ::-1].copy() if chw else arr[::-1].copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _as_np(img)
    if isinstance(padding, int):
        pl = pt = pr = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    hwc, was_chw = _to_hwc(arr if arr.ndim == 3 else arr[..., None])
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(hwc, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kw)
    out = _from_hwc(out, was_chw)
    if arr.ndim == 2:
        out = out[..., 0] if not was_chw else out[0]
    return out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotation via inverse affine sampling (nearest / bilinear)."""
    arr = _as_np(img)
    squeeze2d = arr.ndim == 2
    if squeeze2d:
        arr = arr[..., None]
    hwc, was_chw = _to_hwc(arr)
    h, w = hwc.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        new_w = int(np.ceil(abs(w * cos) + abs(h * sin)))
        new_h = int(np.ceil(abs(w * sin) + abs(h * cos)))
    else:
        new_w, new_h = w, h
    oy, ox = (new_h - 1) / 2.0, (new_w - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(new_h), np.arange(new_w), indexing="ij")
    # inverse rotation from output to input coords
    xs = (xx - ox) * cos + (yy - oy) * sin + cx
    ys = -(xx - ox) * sin + (yy - oy) * cos + cy
    if interpolation == "bilinear":
        x0 = np.floor(xs).astype(int)
        y0 = np.floor(ys).astype(int)
        dx = (xs - x0)[..., None]
        dy = (ys - y0)[..., None]

        def sample(yi, xi):
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            v = hwc[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)].astype(np.float32)
            return np.where(valid[..., None], v, fill)

        out = ((1 - dy) * (1 - dx) * sample(y0, x0)
               + (1 - dy) * dx * sample(y0, x0 + 1)
               + dy * (1 - dx) * sample(y0 + 1, x0)
               + dy * dx * sample(y0 + 1, x0 + 1))
        out = out.astype(hwc.dtype)
    else:
        xi = np.round(xs).astype(int)
        yi = np.round(ys).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = hwc[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
        out = np.where(valid[..., None], out, fill).astype(hwc.dtype)
    out = _from_hwc(out, was_chw)
    if squeeze2d:
        out = out[0] if was_chw else out[..., 0]
    return out


def adjust_brightness(img, brightness_factor):
    arr = _as_np(img)
    out = arr.astype(np.float32) * brightness_factor
    return out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out


def adjust_contrast(img, contrast_factor):
    arr = _as_np(img)
    f = arr.astype(np.float32)
    hwc, _ = _to_hwc(f if f.ndim == 3 else f[..., None])
    # grayscale mean (ITU-R 601 luma) as the contrast pivot, like the ref
    gray = hwc[..., 0] * 0.299 + hwc[..., -1] * 0.114 + \
        (hwc[..., 1] if hwc.shape[-1] >= 2 else hwc[..., 0]) * 0.587
    mean = gray.mean()
    out = f * contrast_factor + mean * (1 - contrast_factor)
    return out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out


def adjust_saturation(img, saturation_factor):
    arr = _as_np(img)
    f = arr.astype(np.float32)
    hwc, was_chw = _to_hwc(f if f.ndim == 3 else f[..., None])
    gray = (hwc[..., :1] * 0.299 + hwc[..., 1:2] * 0.587
            + hwc[..., 2:3] * 0.114) if hwc.shape[-1] == 3 else hwc
    out = hwc * saturation_factor + gray * (1 - saturation_factor)
    out = _from_hwc(out, was_chw)
    return out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out


def adjust_hue(img, hue_factor):
    """Hue rotation in HSV space (reference semantics, hue_factor in
    [-0.5, 0.5])."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _as_np(img)
    f = arr.astype(np.float32)
    hwc, was_chw = _to_hwc(f if f.ndim == 3 else f[..., None])
    if hwc.shape[-1] != 3:
        return arr
    scale = 255.0 if arr.dtype == np.uint8 else 1.0
    rgb = hwc / scale
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6).astype(int)
    fpart = h * 6 - i
    p = v * (1 - s)
    q = v * (1 - fpart * s)
    t = v * (1 - (1 - fpart) * s)
    i = i % 6
    out = np.choose(i[..., None] * 0 + np.arange(3)[None, None, :] * 0 + i[..., None],
                    [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
                     np.stack([p, v, t], -1), np.stack([p, q, v], -1),
                     np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = (out * scale)
    out = _from_hwc(out, was_chw)
    return out.clip(0, 255).astype(np.uint8) if arr.dtype == np.uint8 else out


def to_grayscale(img, num_output_channels=1):
    arr = _as_np(img)
    f = arr.astype(np.float32)
    hwc, was_chw = _to_hwc(f if f.ndim == 3 else f[..., None])
    if hwc.shape[-1] == 3:
        gray = (hwc[..., :1] * 0.299 + hwc[..., 1:2] * 0.587
                + hwc[..., 2:3] * 0.114)
    else:
        gray = hwc[..., :1]
    out = np.repeat(gray, num_output_channels, axis=-1)
    out = _from_hwc(out, was_chw)
    return out.astype(np.uint8) if arr.dtype == np.uint8 else out


def erase(img, i, j, h, w, v, inplace=False):
    arr = _as_np(img)
    out = arr if inplace else arr.copy()
    if _is_chw(out):
        out[:, i:i + h, j:j + w] = v
    else:
        out[i:i + h, j:j + w] = v
    if isinstance(img, Tensor):
        return Tensor(out)
    return out


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        f = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = min(value, 0.5)

    def _apply_image(self, img):
        return adjust_hue(img, pyrandom.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        order = list(self.transforms)
        pyrandom.shuffle(order)
        for t in order:
            img = t(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = pyrandom.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.args = (padding, fill, padding_mode)

    def _apply_image(self, img):
        return pad(img, *self.args)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        arr = _as_np(img)
        if pyrandom.random() > self.prob:
            return img
        if _is_chw(arr):
            h, w = arr.shape[1], arr.shape[2]
        else:
            h, w = arr.shape[0], arr.shape[1]
        area = h * w
        for _ in range(10):
            target = pyrandom.uniform(*self.scale) * area
            aspect = pyrandom.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * aspect)))
            ew = int(round(np.sqrt(target / aspect)))
            if eh < h and ew < w:
                i = pyrandom.randint(0, h - eh)
                j = pyrandom.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value,
                             inplace=self.inplace)
        return img
