"""paddle.vision.transforms parity (numpy-array transforms; ref:
python/paddle/vision/transforms/ (U)). Images are HWC numpy arrays or CHW
Tensors; ToTensor converts HWC uint8 -> CHW float32/255."""

from __future__ import annotations

import random as pyrandom

import numpy as np

from ..core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _as_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _as_np(img).astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        import jax

        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        if chw:
            tgt = (arr.shape[0], th, tw)
        else:
            tgt = (th, tw) + ((arr.shape[2],) if arr.ndim == 3 else ())
        method = {"bilinear": "linear", "nearest": "nearest", "bicubic": "cubic"}[self.interpolation]
        out = np.asarray(jax.image.resize(arr.astype(np.float32), tgt, method=method))
        if arr.dtype == np.uint8:
            out = out.clip(0, 255).astype(np.uint8)
        return out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 4
            if chw:
                arr = np.pad(arr, [(0, 0), (p[1], p[3]), (p[0], p[2])])
            else:
                pads = [(p[1], p[3]), (p[0], p[2])] + ([(0, 0)] if arr.ndim == 3 else [])
                arr = np.pad(arr, pads)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        th, tw = self.size
        i = pyrandom.randint(0, max(h - th, 0))
        j = pyrandom.randint(0, max(w - tw, 0))
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _as_np(img)
        if pyrandom.random() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
            return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = _as_np(img)
        if pyrandom.random() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
            return arr[:, ::-1].copy() if chw else arr[::-1].copy()
        return arr


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        import math

        arr = _as_np(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = h * w
        for _ in range(10):
            target_area = area * pyrandom.uniform(*self.scale)
            aspect = math.exp(pyrandom.uniform(math.log(self.ratio[0]), math.log(self.ratio[1])))
            cw = int(round(math.sqrt(target_area * aspect)))
            ch = int(round(math.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                i = pyrandom.randint(0, h - ch)
                j = pyrandom.randint(0, w - cw)
                crop = arr[:, i:i + ch, j:j + cw] if chw else arr[i:i + ch, j:j + cw]
                return self._resize(crop)
        return self._resize(CenterCrop(min(h, w))(arr))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _as_np(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        arr = _as_np(img).astype(np.float32)
        factor = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        return (arr * factor).clip(0, 255).astype(np.uint8) if _as_np(img).dtype == np.uint8 else arr * factor


# functional API
def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = _as_np(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
    return arr[:, :, ::-1].copy() if chw else arr[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def crop(img, top, left, height, width):
    arr = _as_np(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] < arr.shape[2]
    if chw:
        return arr[:, top:top + height, left:left + width]
    return arr[top:top + height, left:left + width]
