"""paddle.distributed.stream parity (ref: communication/stream/ (U)).

The reference's stream variants run collectives on a caller-chosen CUDA
stream for manual compute/comm overlap. On TPU, XLA's latency-hiding
scheduler owns overlap — there are no user streams — so the stream API is
the plain collective (same signature, `use_calc_stream` accepted and
ignored), keeping reference scripts working unchanged.
"""

from .communication import (  # noqa: F401
    all_reduce, all_gather, reduce_scatter, broadcast, scatter, reduce,
    alltoall, alltoall_single, send, recv,
)


def _accepting_stream_kw(fn):
    import functools

    @functools.wraps(fn)
    def wrapped(*args, use_calc_stream=False, **kw):
        return fn(*args, **kw)

    return wrapped


all_reduce = _accepting_stream_kw(all_reduce)
all_gather = _accepting_stream_kw(all_gather)
reduce_scatter = _accepting_stream_kw(reduce_scatter)
broadcast = _accepting_stream_kw(broadcast)
scatter = _accepting_stream_kw(scatter)
reduce = _accepting_stream_kw(reduce)
alltoall = _accepting_stream_kw(alltoall)
alltoall_single = _accepting_stream_kw(alltoall_single)
send = _accepting_stream_kw(send)
recv = _accepting_stream_kw(recv)
