"""Long-context attention: ring (context parallel) + Ulysses (head scatter).

Reference parity: the 'sep' topology axis + all_to_all primitives live in
core Paddle (SURVEY.md §2.2 P16); ring/context-parallel flash attention and
Ulysses attention are implemented in the PaddleNLP ecosystem on top of them.
Per SURVEY.md §5 (long-context is first-class here) both live in-core:

* **ring_flash_attention** — q/k/v sharded on the sequence dim over the ring
  axis; N steps of blockwise attention with online log-sum-exp combination
  while k/v blocks rotate around the ring via `lax.ppermute` (ICI
  neighbor-exchange; XLA overlaps the permute with the block compute). The
  causal schedule masks block pairs by origin rank: full attention for
  earlier blocks, intra-block causal on the diagonal, zero contribution for
  later blocks.
* **ulysses_attention** — `lax.all_to_all` swaps the sequence shard for a
  head shard (DeepSpeed-Ulysses), runs ordinary (flash) attention on full
  sequences for H/N heads, and swaps back. Needs num_heads % ring_size == 0.

Both are pure jax functions over arrays (use inside shard_map); Tensor-level
wrappers route through op_call.apply so tape autograd records them.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_call import apply
from ..ops.pallas.flash import (
    _block_sizes,
    _flash_bwd,
    _flash_fwd,
    _interpret_default,
    _pad_seq,
)
from . import collective_ctx
from .shard_map_compat import axis_size as _axis_size

NEG_INF = -1e30


def _to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _to_bshd(x, b, h):
    _, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _ring_mode(src, idx):
    """Causal ring schedule: 0 = full (block from an earlier rank), 1 =
    intra-block causal (own block, the diagonal), 2 = fully masked (later
    rank). Exactly the selected branch executes (lax.switch)."""
    return jnp.where(src == idx, 1, jnp.where(src > idx, 2, 0))


def _ring_fwd_res(q, k, v, causal, scale, axis_name, interpret):
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"GQA needs q heads {h} divisible by kv heads {hkv}")
    q_per_kv = h // hkv
    perm = [(i, (i + 1) % n) for i in range(n)]

    bq, bk = _block_sizes(s_local, s_local, d)
    qp, _ = _pad_seq(_to_bhsd(q), bq)
    kp, _ = _pad_seq(_to_bhsd(k), bk)
    vp, _ = _pad_seq(_to_bhsd(v), bk)
    sp = qp.shape[1]
    bh = qp.shape[0]

    def attend(is_causal):
        def f(kk, vv):
            o, lse = _flash_fwd(qp, kk, vv, scale, is_causal, interpret,
                                kv_len=s_local, q_per_kv=q_per_kv,
                                q_len=s_local)
            return o.astype(jnp.float32), lse
        return f

    def masked(kk, vv):
        return (jnp.zeros((bh, sp, d), jnp.float32),
                jnp.full((bh, sp, 1), NEG_INF, jnp.float32))

    def step(carry, t):
        kk, vv, m_run, num, den = carry
        src = (idx - t) % n  # origin rank of the k/v block we hold now
        if causal:
            out_b, lse_b = lax.switch(
                _ring_mode(src, idx), [attend(False), attend(True), masked],
                kk, vv)
        else:
            out_b, lse_b = attend(False)(kk, vv)

        # online log-sum-exp combine of NORMALIZED block outputs:
        # out_total = Σ_b out_b · softmax_b(lse)
        m_new = jnp.maximum(m_run, lse_b)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(lse_b - m_new)
        num = num * alpha + out_b * beta
        den = den * alpha + beta
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (kk, vv, m_new, num, den), None

    m0 = jnp.full((bh, sp, 1), NEG_INF, jnp.float32)
    num0 = jnp.zeros((bh, sp, d), jnp.float32)
    den0 = jnp.zeros((bh, sp, 1), jnp.float32)
    (_, _, m_run, num, den), _ = lax.scan(
        step, (kp, vp, m0, num0, den0), jnp.arange(n))
    den = jnp.maximum(den, 1e-30)
    outp = (num / den).astype(q.dtype)          # padded [BH, Sp, D]
    lsep = m_run + jnp.log(den)                 # global lse [BH, Sp, 1]
    out = _to_bshd(outp[:, :s_local], b, h)
    return out, (qp, kp, vp, outp, lsep)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_core(q, k, v, causal, scale, axis_name, interpret):
    out, _ = _ring_fwd_res(q, k, v, causal, scale, axis_name, interpret)
    return out


def _ring_core_fwd(q, k, v, causal, scale, axis_name, interpret):
    return _ring_fwd_res(q, k, v, causal, scale, axis_name, interpret)


def _ring_core_bwd(causal, scale, axis_name, interpret, res, g):
    """Second ring pass: per step, the Pallas flash backward with the GLOBAL
    lse/delta yields this rank's exact dq contribution plus dk/dv for the
    visiting block; dk/dv accumulators rotate in lockstep with k/v, so after
    the full cycle each lands back on its owner."""
    qp, kp, vp, outp, lsep = res
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, d = g.shape
    hkv_bh = kp.shape[0]
    q_per_kv = qp.shape[0] // hkv_bh
    perm = [(i, (i + 1) % n) for i in range(n)]

    dop = _to_bhsd(g)
    dop = jnp.pad(dop, ((0, 0), (0, qp.shape[1] - dop.shape[1]), (0, 0)))
    delta = jnp.sum(dop.astype(jnp.float32) * outp.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def grad_block(is_causal):
        def f(kk, vv):
            return _flash_bwd(qp, kk, vv, outp, lsep, dop, scale, is_causal,
                              interpret, kv_len=s_local, q_per_kv=q_per_kv,
                              q_len=s_local, delta=delta)
        return f

    def grad_masked(kk, vv):
        return (jnp.zeros(qp.shape, qp.dtype),
                jnp.zeros(kp.shape, kp.dtype),
                jnp.zeros(vp.shape, vp.dtype))

    def step(carry, t):
        kk, vv, dq_acc, dk_acc, dv_acc = carry
        src = (idx - t) % n
        if causal:
            dq_c, dk_c, dv_c = lax.switch(
                _ring_mode(src, idx),
                [grad_block(False), grad_block(True), grad_masked], kk, vv)
        else:
            dq_c, dk_c, dv_c = grad_block(False)(kk, vv)
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_acc = dk_acc + dk_c.astype(jnp.float32)
        dv_acc = dv_acc + dv_c.astype(jnp.float32)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, perm)
        return (kk, vv, dq_acc, dk_acc, dv_acc), None

    dq0 = jnp.zeros(qp.shape[:2] + (d,), jnp.float32)
    dkv0 = jnp.zeros(kp.shape[:2] + (d,), jnp.float32)
    (_, _, dq, dk, dv), _ = lax.scan(
        step, (kp, vp, dq0, dkv0, dkv0), jnp.arange(n))
    dq = _to_bshd(dq[:, :s_local].astype(qp.dtype), b, h)
    dk = _to_bshd(dk[:, :s_local].astype(kp.dtype), b, hkv_bh // b)
    dv = _to_bshd(dv[:, :s_local].astype(vp.dtype), b, hkv_bh // b)
    return dq, dk, dv


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_flash_attention_arrays(q, k, v, causal=False, scale=None,
                                axis_name="sep", interpret=None):
    """[B, S_local, H, D] ring (context-parallel) attention inside shard_map
    over `axis_name`, built on the Pallas flash kernel (SURVEY.md §7.6d): each
    ring step runs blockwise online-softmax flash attention on the resident
    k/v block — no dense S_local×S_local score tile is ever materialized — and
    k/v rotate via lax.ppermute so XLA overlaps the ICI hop with compute. The
    causal schedule picks exactly one branch per step (lax.switch): full
    attention for blocks from earlier ranks, intra-block causal on the
    diagonal, skip for later ranks. k/v may carry fewer heads (GQA).
    Differentiable via a hand-written ring backward (global-lse flash bwd per
    step with rotating dk/dv accumulators)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _interpret_default()
    return _ring_core(q, k, v, bool(causal), float(scale), axis_name,
                      bool(interpret))


def ulysses_attention_arrays(q, k, v, causal=False, scale=None,
                             axis_name="sep", attn_fn=None):
    """Ulysses: all_to_all seq-shard -> head-shard, attend, swap back."""
    n = _axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"num_heads {h} not divisible by sep degree {n}")

    def seq2head(x):
        # [B, S/N, H, D] -> [B, S, H/N, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    if attn_fn is None:
        from ..ops.flash_attention import flash_attention_arrays as attn_fn
    out = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    return head2seq(out)


# ------------------------------------------------------------ Tensor level

def _wrap(fn_arrays):
    @functools.wraps(fn_arrays)
    def op(q, k, v, causal=False, scale=None, axis_name="sep", group=None):
        name = getattr(group, "axis_name", None) or axis_name
        if collective_ctx.current_axis(name) is None:
            # sep=1 degenerate: ordinary attention
            from ..ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        return apply(
            lambda a, b, c: fn_arrays(a, b, c, causal=causal, scale=scale,
                                      axis_name=name),
            q, k, v, _op_name=fn_arrays.__name__)

    return op


ring_flash_attention = _wrap(ring_flash_attention_arrays)
ulysses_attention = _wrap(ulysses_attention_arrays)
