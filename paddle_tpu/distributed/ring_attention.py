"""Long-context attention: ring (context parallel) + Ulysses (head scatter).

Reference parity: the 'sep' topology axis + all_to_all primitives live in
core Paddle (SURVEY.md §2.2 P16); ring/context-parallel flash attention and
Ulysses attention are implemented in the PaddleNLP ecosystem on top of them.
Per SURVEY.md §5 (long-context is first-class here) both live in-core:

* **ring_flash_attention** — q/k/v sharded on the sequence dim over the ring
  axis; N steps of blockwise attention with online log-sum-exp combination
  while k/v blocks rotate around the ring via `lax.ppermute` (ICI
  neighbor-exchange; XLA overlaps the permute with the block compute). The
  causal schedule masks block pairs by origin rank: full attention for
  earlier blocks, intra-block causal on the diagonal, zero contribution for
  later blocks.
* **ulysses_attention** — `lax.all_to_all` swaps the sequence shard for a
  head shard (DeepSpeed-Ulysses), runs ordinary (flash) attention on full
  sequences for H/N heads, and swaps back. Needs num_heads % ring_size == 0.

Both are pure jax functions over arrays (use inside shard_map); Tensor-level
wrappers route through op_call.apply so tape autograd records them.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..core.op_call import apply
from . import collective_ctx

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mode, q_off, k_off):
    """One [B, Sq, H, D] x [B, Sk, H, D] attention block.

    mode: 0 = full, 1 = causal w/ global offsets, 2 = masked out entirely.
    Returns (unnormalized-out-factors): softmax numerator out and row lse.
    """
    s = jnp.einsum("bshd,bthd->bhst", q, k,
                   preferred_element_type=jnp.float32) * scale
    sq, sk = s.shape[-2], s.shape[-1]
    if mode == 1:
        qi = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kj = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(qi >= kj, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # guard all-masked rows
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhst,bthd->bshd", (p / l).astype(v.dtype), v)
    lse = (m + jnp.log(l))[..., 0]  # [B, H, Sq]
    # out is the NORMALIZED block output; lse its log-softmax mass, so blocks
    # combine as out_total = Σ_b out_b·softmax_b(lse)
    return out.astype(jnp.float32), lse


def ring_flash_attention_arrays(q, k, v, causal=False, scale=None,
                                axis_name="sep"):
    """[B, S_local, H, D] ring attention inside shard_map over `axis_name`."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_local = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        kk, vv, m_run, num, den = carry
        src = (idx - t) % n  # origin rank of the k/v block we hold now

        # block score vs this kv block, with the causal ring schedule
        if causal:
            # diagonal: intra-block causal; earlier src: full; later: masked
            out_full, lse_full = _block_attn(q, kk, vv, scale, 0, 0, 0)
            out_diag, lse_diag = _block_attn(
                q, kk, vv, scale, 1, 0, 0)
            is_diag = (src == idx)
            is_later = src > idx
            out_b = jnp.where(is_diag, out_diag, out_full)
            lse_b = jnp.where(is_diag, lse_diag, lse_full)
            lse_b = jnp.where(is_later, NEG_INF, lse_b)
            out_b = jnp.where(is_later, 0.0, out_b)
        else:
            out_b, lse_b = _block_attn(q, kk, vv, scale, 0, 0, 0)

        # online log-sum-exp combine: running (m, num, den) over blocks
        m_new = jnp.maximum(m_run, lse_b)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(lse_b - m_new)
        num = num * alpha[..., None].transpose(0, 2, 1, 3) \
            + out_b * beta[..., None].transpose(0, 2, 1, 3)
        den = den * alpha + beta
        # rotate kv to the next rank (skip the last, unused, hop)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return (kk, vv, m_new, num, den), None

    b, _, h, d = q.shape
    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    num0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    den0 = jnp.zeros((b, h, s_local), jnp.float32)
    (_, _, _, num, den), _ = lax.scan(
        step, (k, v, m0, num0, den0), jnp.arange(n))
    den = jnp.maximum(den, 1e-30)
    out = num / den[..., None].transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ulysses_attention_arrays(q, k, v, causal=False, scale=None,
                             axis_name="sep", attn_fn=None):
    """Ulysses: all_to_all seq-shard -> head-shard, attend, swap back."""
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(f"num_heads {h} not divisible by sep degree {n}")

    def seq2head(x):
        # [B, S/N, H, D] -> [B, S, H/N, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq2head(q), seq2head(k), seq2head(v)
    if attn_fn is None:
        from ..ops.flash_attention import flash_attention_arrays as attn_fn
    out = attn_fn(qh, kh, vh, causal=causal, scale=scale)
    return head2seq(out)


# ------------------------------------------------------------ Tensor level

def _wrap(fn_arrays):
    @functools.wraps(fn_arrays)
    def op(q, k, v, causal=False, scale=None, axis_name="sep", group=None):
        name = getattr(group, "axis_name", None) or axis_name
        if collective_ctx.current_axis(name) is None:
            # sep=1 degenerate: ordinary attention
            from ..ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        return apply(
            lambda a, b, c: fn_arrays(a, b, c, causal=causal, scale=scale,
                                      axis_name=name),
            q, k, v, _op_name=fn_arrays.__name__)

    return op


ring_flash_attention = _wrap(ring_flash_attention_arrays)
ulysses_attention = _wrap(ulysses_attention_arrays)
