"""paddle.distributed.split (ref: python/paddle/distributed/collective.py
split() (U)): shard a linear/embedding computation over the model-parallel
group. The reference builds the parallel weights and inserts the collectives
op-by-op; here it constructs the corresponding fleet.meta_parallel layer
(Column/RowParallelLinear, VocabParallelEmbedding) once per call site and
applies it — same math, the collectives compile to XLA named-axis ops."""

from __future__ import annotations

_SPLIT_CACHE = {}


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    from .topology import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    if hcg is None or hcg.get_model_parallel_world_size() < 2:
        raise RuntimeError(
            "paddle.distributed.split needs an initialized model-parallel "
            "group (fleet.init with mp_degree>1)")
    mp = hcg.get_model_parallel_world_size()
    if num_partitions != mp:
        raise ValueError(
            f"num_partitions ({num_partitions}) must equal the "
            f"model-parallel degree ({mp})")

    if name is None:
        # cache per call site, so an unnamed split() inside forward reuses
        # its layer (and its weights) across steps
        import inspect

        fr = inspect.stack()[1]
        name = f"{fr.filename}:{fr.lineno}"
    key = f"{name}_{operation}_{size}_{axis}"
    layer = _SPLIT_CACHE.get(key)
    if layer is None:
        from .fleet.meta_parallel import (
            ColumnParallelLinear,
            RowParallelLinear,
            VocabParallelEmbedding,
        )

        if operation == "linear":
            in_f, out_f = size
            if axis == 1:
                layer = ColumnParallelLinear(
                    in_f, out_f, weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    gather_output=gather_out)
            elif axis == 0:
                layer = RowParallelLinear(
                    in_f, out_f, weight_attr=weight_attr,
                    has_bias=bias_attr is not False,
                    input_is_parallel=not gather_out)
            else:
                raise ValueError("linear split axis must be 0 or 1")
        elif operation == "embedding":
            vocab, hidden = size
            layer = VocabParallelEmbedding(vocab, hidden,
                                           weight_attr=weight_attr)
        else:
            raise ValueError(
                f"unknown split operation {operation!r}; use "
                "'linear' or 'embedding'")
        _SPLIT_CACHE[key] = layer  # noqa: PTA402 -- keyed on concrete config, stores a Layer
    return layer(x)
