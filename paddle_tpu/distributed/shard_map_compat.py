"""One shared jax.shard_map compatibility shim.

jax 0.8 moved shard_map out of jax.experimental and renamed the
replication-check kwarg (check_rep -> check_vma). Every caller that wants
to keep working across that boundary imports the pair from here instead of
re-implementing the try/except — the kwarg MUST match what the resolved
function actually accepts, which is decided by inspecting its signature
(ADVICE r5: there is a jax window where the top-level `jax.shard_map`
exists but still takes check_rep, so import location alone is not a
reliable proxy for the kwarg spelling).
"""

import inspect

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax layout
    from jax.experimental.shard_map import shard_map as _shard_map


def _takes_check_vma(fn):
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        # unsignaturable (C accelerated / wrapped): assume the modern
        # spelling, which every jax that hides the signature also uses
        return True
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in params.values()):
        return "check_rep" not in params
    return "check_vma" in params


if _takes_check_vma(_shard_map):
    shard_map = _shard_map

    #: kwargs disabling the output-replication check, matching the signature
    NO_CHECK = {"check_vma": False}
else:
    NO_CHECK = {"check_rep": False}

    def shard_map(*args, check_vma=None, **kwargs):
        # accept the modern kwarg spelling and translate it, so callers
        # written against jax>=0.8 work unchanged on the legacy API
        if check_vma is not None:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map(*args, **kwargs)


def axis_size(axis_name):
    """Static size of a named mesh axis from inside shard_map.

    ``lax.axis_size`` only exists in newer jax; on older releases
    ``lax.psum(1, axis)`` constant-folds to the same static int (no
    collective is emitted for a literal operand), so every mapped-code
    caller (ring attention, MoE EP, mp_ops) resolves through here."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


__all__ = ["shard_map", "NO_CHECK", "axis_size"]
