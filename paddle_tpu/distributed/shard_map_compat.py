"""One shared jax.shard_map compatibility shim.

jax 0.8 moved shard_map out of jax.experimental and renamed the
replication-check kwarg (check_rep -> check_vma). Every caller that wants
to keep working across that boundary imports the pair from here instead of
re-implementing the try/except — the kwarg MUST match the import taken
(the legacy API rejects check_vma and vice versa).
"""

try:
    from jax import shard_map

    #: kwargs disabling the output-replication check, matching the import
    NO_CHECK = {"check_vma": False}
except ImportError:  # older jax layout (and its older kwarg name)
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    NO_CHECK = {"check_rep": False}

    def shard_map(*args, check_vma=None, **kwargs):
        # accept the modern kwarg spelling and translate it, so callers
        # written against jax>=0.8 work unchanged on the legacy API
        if check_vma is not None:
            kwargs.setdefault("check_rep", check_vma)
        return _legacy_shard_map(*args, **kwargs)

__all__ = ["shard_map", "NO_CHECK"]
