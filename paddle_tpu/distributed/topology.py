"""Hybrid-parallel topology over a `jax.sharding.Mesh`.

Reference parity: python/paddle/distributed/fleet/base/topology.py (U) —
`CommunicateTopology` + `HybridCommunicateGroup` build the 4D/5D process grid
[data, pipe, sharding, sep, model] and create an NCCL comm group per axis
(SURVEY.md §2.2 P11).

TPU-native design: the process grid IS a `jax.sharding.Mesh` with named axes
("dp", "pp", "sharding", "sep", "mp"). A "communication group" is not a comm
ring object that owns sockets — it is a *named mesh axis*; collectives become
`lax.psum`/`all_gather`/`ppermute` over the axis name inside `shard_map`, and
XLA lowers them onto ICI (intra-slice) / DCN (multi-slice) links. `Group`
therefore carries only (axis name, size, coordinate), plus enough metadata for
the paddle.distributed API surface (ranks lists, group ids).
"""

from __future__ import annotations

import collections
import itertools

import numpy as np

# paddle axis name -> mesh axis name
_AXIS_ALIASES = {
    "data": "dp",
    "pipe": "pp",
    "sharding": "sharding",
    "sep": "sep",
    "model": "mp",
}
# canonical hybrid order, matching the reference's topology order
HYBRID_ORDER = ("data", "pipe", "sharding", "sep", "model")


def mesh_axis_name(paddle_name: str) -> str:
    return _AXIS_ALIASES.get(paddle_name, paddle_name)


class ReduceOp:
    """paddle.distributed.ReduceOp parity."""

    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communication group == one named axis of the device mesh.

    The reference's Group wraps an NCCL communicator (process_group_nccl.cc);
    here it names a mesh axis so collectives compile to XLA collectives.
    """

    _next_gid = itertools.count(0)

    def __init__(self, axis_name, nranks, rank_in_group=0, ranks=None, mesh=None):
        self.axis_name = axis_name  # mesh axis ('dp', 'mp', ...) or None (world)
        self.nranks = int(nranks)
        self.rank = int(rank_in_group)
        self.ranks = list(ranks) if ranks is not None else list(range(self.nranks))
        self.mesh = mesh
        self.id = next(Group._next_gid)

    @property
    def world_size(self):
        return self.nranks

    @property
    def process_group(self):  # reference API compat
        return self

    def get_group_rank(self, global_rank):
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            return -1

    def is_member(self):
        return True

    def __repr__(self):
        return f"Group(axis={self.axis_name!r}, nranks={self.nranks}, rank={self.rank})"


class CommunicateTopology:
    """Rank-grid arithmetic (reference: CommunicateTopology, topology.py (U))."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or HYBRID_ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        if len(self._dims) != len(self._parallel_names):
            raise ValueError("dims and hybrid_group_names length mismatch")
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        self.world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = dict(zip(all_coords, range(self.world_size)))
        self._rank2coord = dict(zip(self._coord2rank.values(), self._coord2rank.keys()))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def get_rank(self, **kwargs):
        return self._coord2rank[self.coordinate(**kwargs)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All global ranks whose coordinate on `axis_name` equals `index`."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items() if c[axis] == index)

    def get_comm_list(self, axis_name):
        """List of rank-lists, one per communicator along `axis_name`."""
        axis = self._parallel_names.index(axis_name)
        other_ranges = [
            range(d) for i, d in enumerate(self._dims) if i != axis
        ]
        comm_list = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for i in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, i)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


def build_mesh(dims_by_name, devices=None):
    """Build the device mesh for a hybrid topology.

    dims_by_name: ordered {paddle_axis_name: degree}. Degree-1 axes are kept in
    the mesh so PartitionSpecs may always name them.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = int(np.prod(list(dims_by_name.values())))
    if n > len(devices):
        raise ValueError(
            f"topology needs {n} devices, only {len(devices)} available"
        )
    dev = np.array(devices[:n]).reshape(tuple(dims_by_name.values()))
    names = tuple(mesh_axis_name(k) for k in dims_by_name)
    return Mesh(dev, names)


class HybridCommunicateGroup:
    """Reference parity: HybridCommunicateGroup (topology.py (U)).

    Owns the jax Mesh and hands out per-axis Groups. In single-process SPMD
    the "current rank" is a virtual coordinate (default 0 on every axis);
    under multi-process jax.distributed it is the process's first device's
    coordinate.
    """

    def __init__(self, topology: CommunicateTopology, devices=None, global_rank=None):
        self._topo = topology
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self._mesh = build_mesh(dict(zip(names, dims)), devices)
        self.nranks = topology.world_size
        self.global_rank = 0 if global_rank is None else int(global_rank)

        self._dp_degree = self._degree("data")
        self._pp_degree = self._degree("pipe")
        self._sharding_degree = self._degree("sharding")
        self._sep_degree = self._degree("sep")
        self._mp_degree = self._degree("model")

        coord = topology.get_coord(self.global_rank)
        self._groups = {}
        for name in names:
            idx = getattr(coord, name)
            # ranks along this axis that share all *other* coordinates:
            comm = None
            for rl in topology.get_comm_list(name):
                if self.global_rank in rl:
                    comm = rl
                    break
            self._groups[name] = Group(
                mesh_axis_name(name),
                topology.get_dim(name),
                rank_in_group=idx,
                ranks=comm,
                mesh=self._mesh,
            )

        global _HCG
        _HCG = self

    def _degree(self, name):
        try:
            return self._topo.get_dim(name)
        except ValueError:
            return 1

    # ---- mesh access (TPU-native extension) ----
    @property
    def mesh(self):
        return self._mesh

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        # reference returns a ParallelMode enum; keep simple strings
        if self._mp_degree > 1 or self._pp_degree > 1 or self._sharding_degree > 1:
            return "hybrid"
        return "data" if self._dp_degree > 1 else "single"

    # ---- per-axis accessors, reference API names ----
    def get_data_parallel_rank(self):
        return self._groups["data"].rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["data"].ranks[0]

    def get_model_parallel_rank(self):
        return self._groups["model"].rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["model"].ranks[0]

    def get_stage_id(self):
        return self._groups["pipe"].rank

    def get_pipe_parallel_rank(self):
        return self._groups["pipe"].rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._groups["pipe"]

    def get_sharding_parallel_rank(self):
        return self._groups["sharding"].rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return self._groups["sharding"].ranks[0]

    def get_sep_parallel_rank(self):
        return self._groups["sep"].rank

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    def get_global_rank(self):
        return self.global_rank

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)

    # first/last pipeline stage helpers (reference: is_first_stage property)
    @property
    def is_first_stage(self):
        return self.get_stage_id() == 0

    @property
    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1


_HCG = None


def get_hybrid_communicate_group():
    return _HCG


def set_hybrid_communicate_group(hcg):
    global _HCG
    _HCG = hcg


def create_hybrid_communicate_group(dp=1, mp=1, pp=1, sharding=1, sep=1, devices=None):
    """Convenience builder used by fleet.init and tests."""
    topo = CommunicateTopology(
        hybrid_group_names=list(HYBRID_ORDER),
        dims=[dp, pp, sharding, sep, mp],
    )
    return HybridCommunicateGroup(topo, devices=devices)
