"""paddle.distributed.init_parallel_env / DataParallel.

Reference parity: python/paddle/distributed/parallel.py (U) — TCPStore
rendezvous + ProcessGroupNCCL creation + the DataParallel gradient-bucketing
wrapper (SURVEY.md §3.2).

TPU-native design: rendezvous is `jax.distributed.initialize` (coordination
service), one process per host, all devices visible as one mesh. DataParallel
needs no reducer (N9): with the batch sharded over the "dp" axis and params
replicated, XLA's SPMD partitioner inserts and overlaps the gradient
all-reduce itself — the wrapper only annotates shardings and keeps the
reference's API (no_sync, state_dict passthrough).
"""

from __future__ import annotations

import os

import numpy as np

from ..nn.layer.layers import Layer
from .topology import (
    CommunicateTopology,
    Group,
    HybridCommunicateGroup,
    HYBRID_ORDER,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)

_PARALLEL_ENV = None


class ParallelEnv:
    """ref: parallel.py ParallelEnv (U): rank/world-size/device from env."""

    def __init__(self):
        self.rank = int(os.getenv("PADDLE_TRAINER_ID", os.getenv("RANK", "0")))
        self.world_size = int(
            os.getenv("PADDLE_TRAINERS_NUM", os.getenv("WORLD_SIZE", "1"))
        )
        # reference convention allows a comma-separated device list
        # (FLAGS_selected_gpus="0,1,2,3"); first entry is this proc's device
        self.device_id = int(os.getenv("FLAGS_selected_tpus", "0").split(",")[0])
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints = eps.split(",") if eps else []
        self.current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


def init_parallel_env():
    """Initialize the distributed context.

    Multi-host: call `jax.distributed.initialize` using the launcher's env
    contract (PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS — same env names as
    the reference so launch scripts port unchanged). Single host: build a
    1-coordinate data-parallel topology over all local devices.
    """
    global _PARALLEL_ENV
    env = ParallelEnv()
    _PARALLEL_ENV = env

    import jax

    already = False
    try:
        from jax._src import distributed as _jd

        already = _jd.global_state.client is not None
    except Exception:
        pass
    if env.world_size > 1 and env.trainer_endpoints and not already:
        coordinator = env.trainer_endpoints[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=env.world_size,
                process_id=env.rank,
            )
        except RuntimeError as e:
            msg = str(e).lower()
            # "already initialized"/"called once": the import-time hook in
            # paddle_tpu/__init__ (_maybe_init_distributed) won the race —
            # fine. Anything else is a genuine rendezvous failure.
            if "must be called before" in msg:
                raise RuntimeError(
                    "multi-process rendezvous must happen before the XLA "
                    "backend initializes: export the launcher env contract "
                    "(PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS / "
                    "PADDLE_TRAINER_ID) BEFORE `import paddle_tpu` — the "
                    "package then joins the coordination service at import "
                    "time (use python -m paddle_tpu.distributed.launch)"
                ) from e
            if "already" not in msg and "once" not in msg:
                raise

    if get_hybrid_communicate_group() is None:
        ndev = jax.device_count()
        topo = CommunicateTopology(list(HYBRID_ORDER), [ndev, 1, 1, 1, 1])
        set_hybrid_communicate_group(HybridCommunicateGroup(topo))
    return get_hybrid_communicate_group().get_data_parallel_group()


def get_rank(group=None):
    if group is not None:
        return group.rank
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    env = ParallelEnv()
    if env.world_size > 1:
        return env.world_size
    hcg = get_hybrid_communicate_group()
    return hcg.nranks if hcg is not None else 1


def is_initialized():
    return get_hybrid_communicate_group() is not None or _PARALLEL_ENV is not None


class DataParallel(Layer):
    """ref: paddle.DataParallel (parallel.py (U)).

    No gradient reducer on TPU: `jit` over a dp-sharded batch produces the
    allreduce in-program. This wrapper (a) shards input batches over the dp
    mesh axis when a mesh is live, (b) exposes no_sync()/state_dict parity.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*self._shard_inputs(inputs), **kwargs)

    def _shard_inputs(self, inputs):
        hcg = get_hybrid_communicate_group()
        if hcg is None or hcg.get_data_parallel_world_size() == 1:
            return inputs
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..core.tensor import Tensor

        sharding = NamedSharding(hcg.mesh, P("dp"))
        out = []
        for x in inputs:
            if isinstance(x, Tensor) and x.ndim >= 1 and not _is_traced(x._data):
                try:
                    x = Tensor(jax.device_put(x._data, sharding),
                               stop_gradient=x.stop_gradient)
                except ValueError:
                    pass  # batch not divisible by dp degree: leave placement to XLA
            out.append(x)
        return tuple(out)

    def no_sync(self):
        """Gradient-accumulation scope. XLA emits the allreduce only in the
        step that consumes the grads, so this is contextually a no-op."""
        import contextlib

        return contextlib.nullcontext()

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)


def _is_traced(arr):
    return hasattr(arr, "aval") and not hasattr(arr, "addressable_shards")


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: paddle.distributed.spawn. Single-controller jax needs no process
    fan-out on one host — run inline over the visible devices."""
    func(*args)
