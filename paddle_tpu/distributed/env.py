"""Process-level distributed environment (ref: PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM env contract, SURVEY.md §2.2 P21).

On TPU, one process per host; jax.distributed supplies process_index/count
once initialized. Before that (or single-host), the PADDLE_* env vars are
honored so launcher-style scripts behave identically.
"""

from __future__ import annotations

import os


def get_rank():
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_index()
    except Exception:
        pass
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size():
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_count()
    except Exception:
        pass
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))


def is_initialized():
    return _INITIALIZED[0]


_INITIALIZED = [False]


def mark_initialized():
    _INITIALIZED[0] = True
