"""Named-axis context: which mesh axes are live inside the current
shard_map/pjit scope.

The reference routes collectives through explicit process groups
(ProcessGroupNCCL comm rings, SURVEY.md §2.1 N13). TPU-native, a "group" is a
*named mesh axis*; layers ask this registry whether an axis is in scope and
then use psum/all_gather over the axis name. fleet/parallel wrappers push axes
here when they enter a shard_map region.
"""

from __future__ import annotations

import contextlib
import threading


class _Scope(threading.local):
    def __init__(self):
        self.axes = []  # stack of axis-name strings currently mapped


_SCOPE = _Scope()
_SCOPE_EXIT_HOOKS = []
_SCOPE_ENTER_HOOKS = []


def register_scope_exit(fn):
    """Run `fn()` whenever the outermost axis scope exits (used to drop
    per-trace buffers, e.g. pending p2p sends)."""
    _SCOPE_EXIT_HOOKS.append(fn)


def register_scope_enter(fn):
    """Run `fn()` whenever an outermost axis scope is entered — a fresh trace
    must never see buffers left behind by an earlier aborted trace."""
    _SCOPE_ENTER_HOOKS.append(fn)


@contextlib.contextmanager
def axis_scope(*axis_names):
    """Declare that `axis_names` are live named axes (entered by shard_map
    wrappers in distributed.fleet / distributed.parallel)."""
    if not _SCOPE.axes:
        for fn in _SCOPE_ENTER_HOOKS:
            fn()
    _SCOPE.axes.extend(axis_names)
    try:
        yield
    finally:
        for _ in axis_names:
            _SCOPE.axes.pop()
        if not _SCOPE.axes:
            for fn in _SCOPE_EXIT_HOOKS:
                fn()


def current_axis(name):
    return name if name in _SCOPE.axes else None


def axes_in_scope(names):
    return [n for n in names if n in _SCOPE.axes]


def any_axis_in_scope():
    return bool(_SCOPE.axes)


def psum_scoped(value, axis_name):
    import jax

    return jax.lax.psum(value, axis_name)
