"""paddle.distributed parity namespace — populated incrementally; the full
fleet/collective surface lands with the distributed layer."""

from . import collective_ctx
from .collective_ctx import axis_scope
