"""paddle.distributed parity namespace.

Reference: python/paddle/distributed/ (U) — collectives, parallel env, fleet,
hybrid-parallel layers (SURVEY.md §2.2 P9-P23). TPU-native core: a named-axis
jax Mesh replaces comm rings; see topology.py / communication.py.
"""

from . import collective_ctx
from .collective_ctx import axis_scope
from .topology import (
    CommunicateTopology,
    Group,
    HybridCommunicateGroup,
    ReduceOp,
    create_hybrid_communicate_group,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from .communication import (
    all_gather,
    all_gather_object,
    broadcast_object_list,
    scatter_object_list,
    get_backend,
    all_reduce,
    all_to_all,
    all_to_all_single,
    alltoall,
    alltoall_single,
    gather,
    barrier,
    broadcast,
    destroy_process_group,
    get_group,
    isend,
    irecv,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    shift,
    wait,
)
from .parallel import (
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
    spawn,
)
from .recompute import recompute

__all__ = [
    "all_gather", "all_gather_object", "all_reduce", "alltoall",
    "alltoall_single", "all_to_all", "all_to_all_single", "gather", "barrier", "broadcast", "destroy_process_group",
    "get_group", "isend", "irecv", "new_group", "recv", "reduce",
    "reduce_scatter", "scatter", "send", "shift", "wait", "ReduceOp",
    "DataParallel", "ParallelEnv", "get_rank", "get_world_size",
    "init_parallel_env", "is_initialized", "spawn", "recompute",
    "Group", "CommunicateTopology", "HybridCommunicateGroup",
    "get_hybrid_communicate_group", "set_hybrid_communicate_group",
    "create_hybrid_communicate_group", "axis_scope",
]

from . import fleet
from . import sharding
from .ring_attention import ring_flash_attention, ulysses_attention
from . import checkpoint
from . import launch
from . import stream
from .mp_split import split
from . import auto_parallel
from .auto_parallel import (
    DistModel,
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    reshard,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    unshard_dtensor,
)

__all__ += [
    "ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
    "dtensor_from_fn", "reshard", "shard_layer", "shard_optimizer",
    "unshard_dtensor", "DistModel",
]
