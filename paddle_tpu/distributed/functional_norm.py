"""Cross-replica (sync) batch norm (ref: paddle SyncBatchNorm over NCCL
allreduce, SURVEY.md §2.2). TPU-native: the mean/var reduction is a psum over
the named data-parallel mesh axis inside shard_map — XLA turns it into one
fused ICI all-reduce."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_call import apply
from ..core.tensor import Tensor
from ..tensor.creation import _as_t


def sync_batch_norm(x, running_mean, running_var, weight, bias, momentum, epsilon,
                    data_format, axis_name):
    x = _as_t(x)
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)

    def bshape(ndim, c):
        s = [1] * ndim
        s[channel_axis] = c
        return s

    def f(a, *wb):
        # two-moment psum: E[x], E[x^2] across local batch AND the dp axis
        cnt_local = 1.0
        for ax in reduce_axes:
            cnt_local *= a.shape[ax]
        s1 = jnp.sum(a, axis=reduce_axes)
        s2 = jnp.sum(jnp.square(a), axis=reduce_axes)
        s1 = jax.lax.psum(s1, axis_name)
        s2 = jax.lax.psum(s2, axis_name)
        cnt = jax.lax.psum(cnt_local, axis_name)
        mean = s1 / cnt
        var = s2 / cnt - jnp.square(mean)
        out = (a - mean.reshape(bshape(a.ndim, mean.size))) * jax.lax.rsqrt(
            var.reshape(bshape(a.ndim, var.size)) + epsilon
        )
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape(a.ndim, wb[i].size))
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape(a.ndim, wb[i].size))
        return out, mean, var

    args = [x]
    if weight is not None:
        args.append(_as_t(weight))
    if bias is not None:
        args.append(_as_t(bias))
    out, mean, var = apply(f, *args, _op_name="sync_batch_norm")
    if running_mean is not None:
        running_mean._data = running_mean._data * momentum + mean._data * (1 - momentum)
        running_var._data = running_var._data * momentum + var._data * (1 - momentum)
    return out
