"""Activation recomputation (ref: python/paddle/distributed/fleet/recompute/
recompute.py (U), SURVEY.md §2.2 P19).

TPU-native: `jax.checkpoint` (remat) IS recompute — the tape records the
layer's forward as a single remat'd op whose vjp re-runs the forward. RNG
state replay (the reference's get_rng_state_tracker dance) is automatic:
the layer pulls keys from the counter stream, and the same fold_in counters
are replayed inside the remat'd function.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.op_call import apply
from ..core import random_state
from ..core import tape as _tape


def recompute(function, *args, **kwargs):
    """Run `function(*args)` with rematerialized activations.

    Non-tensor kwargs are static; preserve_rng_state is implicit (counter
    streams are replayed deterministically)."""
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    other_mask = [isinstance(a, Tensor) for a in args]
    base_counter = random_state._STATE.stream.counter
    base_key = random_state._STATE.stream.base

    # Parameters captured in the function's closure must become explicit vjp
    # inputs, or their gradients are silently dropped (they'd trace as
    # constants). For Layer callables we thread the whole trainable state.
    from ..nn.layer.layers import Layer

    param_tensors = []
    if isinstance(function, Layer):
        param_tensors = [p for p in function.parameters() if not p.stop_gradient]
    n_args = len(tensor_args)

    def raw_fn(*arrays):
        arg_arrays = arrays[:n_args]
        param_arrays = arrays[n_args:]
        ai = iter(arg_arrays)
        rebuilt = [Tensor(next(ai)) if is_t else orig for is_t, orig in zip(other_mask, args)]
        saved_params = [p._data for p in param_tensors]
        for p, arr in zip(param_tensors, param_arrays):
            p._data = arr
        # replay the SAME rng stream inside every (re)execution
        saved = random_state._STATE.stream
        random_state._STATE.stream = random_state._KeyStream(base_key)
        random_state._STATE.stream.counter = base_counter
        try:
            # Tape OFF inside the remat'd body: gradients flow through the
            # OUTER jax.vjp over this traced function. With the tape on,
            # every inner op would run its own jax.vjp, which expands
            # custom_vjp ops (e.g. the Pallas flash kernel) into their raw
            # forward primitives inside this jaxpr — the outer checkpoint
            # then tries to differentiate bare pallas_call and crashes
            # (and custom bwd rules would be silently ignored). no_grad
            # keeps custom_vjp calls intact in the trace.
            with _tape.no_grad():
                out = function(*rebuilt, **kwargs)
        finally:
            random_state._STATE.stream = saved
            for p, arr in zip(param_tensors, saved_params):
                p._data = arr
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._data for o in outs)

    remat_fn = jax.checkpoint(raw_fn)

    def f(*arrays):
        outs = remat_fn(*arrays)
        return outs[0] if len(outs) == 1 else outs

    return apply(f, *tensor_args, *param_tensors, _op_name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """paddle.incubate.distributed.fleet.recompute_sequential parity: chunk a
    Sequential into segments and recompute each."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions) if isinstance(functions, (list, tuple)) else list(functions)
    n = len(layers)
    per = max(n // max(segments, 1), 1)
    out = args[0] if len(args) == 1 else args

    def run_segment(seg):
        def seg_fn(x):
            for l in seg:
                x = l(x)
            return x

        return seg_fn

    i = 0
    while i < n:
        seg = layers[i:i + per]
        out = recompute(run_segment(seg), out)
        i += per
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """mp-aware recompute (ref: recompute_hybrid.py (U)): under tensor
    parallelism the remat'd forward re-runs the SAME collectives (psum etc.),
    which XLA dedupes/schedules; offload hint maps to jax.checkpoint policies."""
    offload = isinstance(ctx, dict) and ctx.get("offload", False)
    return recompute(function, *args, **kwargs)
