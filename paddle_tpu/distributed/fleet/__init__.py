"""paddle.distributed.fleet (ref: python/paddle/distributed/fleet/ (U),
SURVEY.md P10-P18). TPU-native: strategy-driven wrappers over the hybrid
device mesh."""
from ..topology import (
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from . import meta_parallel
from .utils import sequence_parallel_utils
