"""paddle.distributed.fleet (ref: python/paddle/distributed/fleet/ (U),
SURVEY.md P10-P18). TPU-native: strategy-driven wrappers over the hybrid
device mesh."""
from ..topology import (
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from . import meta_parallel
from .base.distributed_strategy import DistributedStrategy
from .fleet import (
    TensorParallel,
    distributed_model,
    distributed_optimizer,
    distributed_scaler,
    fleet,
    init,
    is_first_worker,
    worker_index,
    worker_num,
)
from .utils import sequence_parallel_utils
