"""DistributedStrategy: every parallelism knob in one typed object.

Reference parity: fleet/base/distributed_strategy.py (U) — a protobuf-backed
bag of strategy flags (SURVEY.md §5 config tiers). TPU-native design: plain
typed Python (the north star's "one typed config system"); the same attribute
surface (`hybrid_configs`, `amp`, `recompute`, `sharding`, pipeline/amp/
sharding `*_configs` dicts) so reference training scripts port unchanged —
protobuf serialization is replaced by plain dict round-tripping.
"""

from __future__ import annotations

import copy

_HYBRID_DEFAULTS = {
    "dp_degree": -1,   # -1: absorb remaining devices (reference semantics)
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
}

_AMP_DEFAULTS = {
    "init_loss_scaling": 32768.0,
    "incr_every_n_steps": 1000,
    "decr_every_n_nan_or_inf": 2,
    "incr_ratio": 2.0,
    "decr_ratio": 0.5,
    "use_dynamic_loss_scaling": True,
    "use_pure_fp16": False,
    "use_fp16_guard": False,
    "use_bf16": True,  # TPU default: bf16 needs no loss scaling
    "custom_white_list": [],
    "custom_black_list": [],
}

_SHARDING_DEFAULTS = {
    "sharding_degree": 1,
    "stage": 1,
    "offload": False,
    "segment_broadcast_MB": 32.0,
}

_PIPELINE_DEFAULTS = {
    "accumulate_steps": 1,
    "micro_batch_size": 1,
    "enable_partial_send_recv": True,
    "schedule_mode": "1F1B",
}

_RECOMPUTE_DEFAULTS = {
    "checkpoints": [],
    "enable_offload": False,
}

_GRADIENT_MERGE_DEFAULTS = {
    "k_steps": 1,
    "avg": True,
}

_LAMB_DEFAULTS = {
    "lamb_weight_decay": 0.01,
    "exclude_from_weight_decay": [],
}


class DistributedStrategy:
    def __init__(self):
        self.amp = False
        self.recompute = False
        self.sharding = False
        self.gradient_merge = False
        self.lamb = False
        self.lars = False
        self.fuse_all_reduce_ops = True  # XLA fuses; kept for API parity
        self.find_unused_parameters = False
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self._hybrid_configs = dict(_HYBRID_DEFAULTS)
        self._amp_configs = dict(_AMP_DEFAULTS)
        self._sharding_configs = dict(_SHARDING_DEFAULTS)
        self._pipeline_configs = dict(_PIPELINE_DEFAULTS)
        self._recompute_configs = dict(_RECOMPUTE_DEFAULTS)
        self._gradient_merge_configs = dict(_GRADIENT_MERGE_DEFAULTS)
        self._lamb_configs = dict(_LAMB_DEFAULTS)

    # -- config dicts keep reference update-in-place semantics ------------
    @property
    def hybrid_configs(self):
        return self._hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, cfg):
        unknown = set(cfg) - set(_HYBRID_DEFAULTS)
        if unknown:
            raise ValueError(f"unknown hybrid_configs keys: {sorted(unknown)}")
        self._hybrid_configs.update(cfg)

    @property
    def amp_configs(self):
        return self._amp_configs

    @amp_configs.setter
    def amp_configs(self, cfg):
        self._amp_configs.update(cfg)

    @property
    def sharding_configs(self):
        return self._sharding_configs

    @sharding_configs.setter
    def sharding_configs(self, cfg):
        self._sharding_configs.update(cfg)

    @property
    def pipeline_configs(self):
        return self._pipeline_configs

    @pipeline_configs.setter
    def pipeline_configs(self, cfg):
        self._pipeline_configs.update(cfg)

    @property
    def recompute_configs(self):
        return self._recompute_configs

    @recompute_configs.setter
    def recompute_configs(self, cfg):
        self._recompute_configs.update(cfg)

    @property
    def gradient_merge_configs(self):
        return self._gradient_merge_configs

    @gradient_merge_configs.setter
    def gradient_merge_configs(self, cfg):
        self._gradient_merge_configs.update(cfg)

    @property
    def lamb_configs(self):
        return self._lamb_configs

    @lamb_configs.setter
    def lamb_configs(self, cfg):
        self._lamb_configs.update(cfg)

    # -- helpers ----------------------------------------------------------
    def hybrid_degrees(self, n_devices):
        """Resolve degrees, absorbing remaining devices into dp_degree=-1."""
        h = self._hybrid_configs
        known = (h["mp_degree"] * h["pp_degree"] * h["sharding_degree"]
                 * h["sep_degree"])
        dp = h["dp_degree"]
        if dp in (-1, None):
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by mp*pp*sharding*sep={known}")
            dp = n_devices // known
        if dp * known != n_devices:
            raise ValueError(
                f"hybrid degrees {dp}*{known} != device count {n_devices}")
        return {"dp": dp, "mp": h["mp_degree"], "pp": h["pp_degree"],
                "sharding": h["sharding_degree"], "sep": h["sep_degree"]}

    def to_dict(self):
        return {
            "amp": self.amp, "recompute": self.recompute,
            "sharding": self.sharding,
            "hybrid_configs": copy.deepcopy(self._hybrid_configs),
            "amp_configs": copy.deepcopy(self._amp_configs),
            "sharding_configs": copy.deepcopy(self._sharding_configs),
            "pipeline_configs": copy.deepcopy(self._pipeline_configs),
            "recompute_configs": copy.deepcopy(self._recompute_configs),
            "gradient_merge": self.gradient_merge,
            "gradient_merge_configs": copy.deepcopy(self._gradient_merge_configs),
            "lamb": self.lamb,
            "lamb_configs": copy.deepcopy(self._lamb_configs),
        }

    def __repr__(self):
        import json

        return "DistributedStrategy(" + json.dumps(self.to_dict(), indent=2,
                                                   default=str) + ")"
