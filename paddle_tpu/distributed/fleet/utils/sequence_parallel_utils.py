"""Megatron-style sequence parallelism.

Reference parity: fleet/utils/sequence_parallel_utils.py (U) — ScatterOp /
GatherOp on the sequence dim tied to the mp group,
`ColumnSequenceParallelLinear`, `RowSequenceParallelLinear`,
`mark_as_sequence_parallel_parameter` (SURVEY.md §2.2 P15).

TPU-native design: SP is the reduce_scatter/all_gather placement mode of TP —
the all-gather before a column-parallel matmul and the reduce-scatter after a
row-parallel one, both along the sequence dim over the 'mp' axis. jax derives
the correct vjps (all_gather ↔ psum_scatter), so no hand-written backward
pairs are needed. Layer-norm params living in the sequence-parallel region
are tagged `sequence_parallel=True` so the hybrid optimizer can all-reduce
their grads over mp (they see only 1/mp of the tokens per rank).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ....core.op_call import apply
from ....nn import functional as F
from ....nn.initializer import XavierNormal
from ....nn.layer.layers import Layer
from ... import collective_ctx
from ...shard_map_compat import axis_size as _axis_size
from ...topology import get_hybrid_communicate_group
from ..layers.mpu import mp_ops

_SEQ_AXIS = 0  # reference keeps activations [s, b, h] in SP regions; we keep
               # [b, s, h] and scatter dim 1
_DEFAULT_SP_DIM = 1


def _live(world):
    return world > 1 and collective_ctx.current_axis("mp") is not None


def _world():
    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg is not None else 1


def scatter(t, axis=_DEFAULT_SP_DIM):
    """ScatterOp: forward keeps this rank's sequence block; backward
    all-gathers (derived by jax from dynamic_slice under shard_map)."""
    if not _live(_world()):
        return t

    def f(x):
        n = _axis_size("mp")
        i = lax.axis_index("mp")
        size = x.shape[axis] // n
        return lax.dynamic_slice_in_dim(x, i * size, size, axis=axis)

    return apply(f, t)


def all_gather(t, axis=_DEFAULT_SP_DIM):
    """GatherOp: forward all-gathers sequence blocks; backward reduce-scatters."""
    if not _live(_world()):
        return t
    return apply(lambda x: mp_ops.gather_axis(x, "mp", axis), t)


def reduce_scatter(t, axis=_DEFAULT_SP_DIM):
    """forward reduce-scatter over mp along the sequence dim; backward
    all-gathers."""
    if not _live(_world()):
        return t
    return apply(lambda x: mp_ops.reduce_scatter_axis(x, "mp", axis), t)


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True
    return param


def is_sequence_parallel_parameter(param):
    return getattr(param, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=False):
    """Compat shim: under SPMD the mp-allreduce of SP-region param grads is
    emitted by the hybrid optimizer (see HybridParallelOptimizer), not by
    backward hooks — nothing to register eagerly."""
    return model


class ColumnSequenceParallelLinear(Layer):
    """all-gather(seq) → column-parallel matmul; input/output stay
    sequence-sharded outside, hidden-sharded inside."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None, name=None):
        super().__init__()
        self._world = _mp_world = _world()
        self._group = mp_group
        self.gather_output = gather_output
        if out_features % max(self._world, 1):
            raise ValueError("out_features not divisible by mp degree")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = self._world > 1
        self.weight._sharding_axes = (None, "mp")
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        if self.bias is not None:
            self.bias._sharding_axes = ("mp",)

    def forward(self, x):
        if _live(self._world):
            x = all_gather(x)
            y = apply(lambda a, w: jnp.matmul(a, w), x, self.weight)
            if self.bias is not None:
                y = apply(lambda a, b: a + b, y, self.bias)
            if self.gather_output:
                y = mp_ops._c_concat(y, self._group)
            return y
        return F.linear(x, self.weight, self.bias)


class RowSequenceParallelLinear(Layer):
    """row-parallel matmul → reduce-scatter(seq) instead of allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        self._world = _world()
        self._group = mp_group
        self.input_is_parallel = input_is_parallel
        if in_features % max(self._world, 1):
            raise ValueError("in_features not divisible by mp degree")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        self.weight.is_distributed = self._world > 1
        self.weight._sharding_axes = ("mp", None)
        self.bias = (self.create_parameter([out_features], is_bias=True)
                     if has_bias else None)
        if self.bias is not None:
            self.bias._sharding_axes = (None,)
            mark_as_sequence_parallel_parameter(self.bias)

    def forward(self, x):
        if _live(self._world):
            if not self.input_is_parallel:
                x = mp_ops._c_split(x, self._group)
            y = apply(lambda a, w: jnp.matmul(a, w), x, self.weight)
            y = reduce_scatter(y)
            if self.bias is not None:
                y = apply(lambda a, b: a + b, y, self.bias)
            return y
        return F.linear(x, self.weight, self.bias)


GatherOp = all_gather
ScatterOp = scatter
AllGatherOp = all_gather
ReduceScatterOp = reduce_scatter
