"""The Fleet facade: init → distributed_model → distributed_optimizer.

Reference parity: fleet/fleet.py (U) — the singleton users drive hybrid
training through (SURVEY.md §2.2 P10, §3.3). TPU-native: `init` builds the
hybrid mesh (HybridCommunicateGroup over jax devices) from
DistributedStrategy.hybrid_configs; `distributed_model` picks the runtime
wrapper (PipelineParallel / TensorParallel / DataParallel); the optimizer
wrapper adds hybrid-aware grad clipping. There is no role maker service —
rendezvous is jax.distributed (see distributed.parallel.init_parallel_env).
"""

from __future__ import annotations

from ...nn.layer.layers import Layer
from .. import collective_ctx
from ..parallel import DataParallel, init_parallel_env
from ..topology import (
    HybridCommunicateGroup,
    create_hybrid_communicate_group,
    get_hybrid_communicate_group,
)
from .base.distributed_strategy import DistributedStrategy
from .meta_parallel import PipelineParallel
from .meta_parallel.parallel_layers.pp_layers import PipelineLayer


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._initialized = False

    # ------------------------------------------------------------- init
    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        import jax

        self._strategy = strategy or DistributedStrategy()
        degrees = self._strategy.hybrid_degrees(jax.device_count())
        create_hybrid_communicate_group(**degrees)
        init_parallel_env()
        self._initialized = True
        return self

    @property
    def is_initialized(self):
        return self._initialized

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        hcg = get_hybrid_communicate_group()
        return hcg.get_global_rank() if hcg else 0

    def worker_num(self):
        hcg = get_hybrid_communicate_group()
        return hcg.nranks if hcg else 1

    def get_hybrid_communicate_group(self):
        return get_hybrid_communicate_group()

    @property
    def strategy(self):
        return self._strategy

    # ------------------------------------------------------- model/opt
    def distributed_model(self, model):
        """ref fleet.distributed_model: wrap for the active parallelism."""
        hcg = get_hybrid_communicate_group()
        if hcg is None:
            raise RuntimeError("call fleet.init() first")
        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg, self._strategy)
        if hcg.get_model_parallel_world_size() > 1:
            return TensorParallel(model, hcg, strategy=self._strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        from ...static.graph import in_static_mode

        if in_static_mode():
            # static mode (ref: each strategy flag selects a meta-optimizer
            # that rewrites the program before Executor.run — P20)
            from .meta_optimizers.static_meta_optimizer import (
                StaticMetaOptimizer,
            )

            return StaticMetaOptimizer(optimizer,
                                       strategy or self._strategy)
        from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (
            HybridParallelOptimizer,
        )

        hcg = get_hybrid_communicate_group()
        return HybridParallelOptimizer(optimizer, hcg,
                                       strategy or self._strategy)

    def distributed_scaler(self, scaler):
        """AMP GradScaler is hybrid-safe as-is: inf detection and scale state
        are computed inside the one compiled step on replicated values."""
        return scaler

    # ------------------------------------------------------- state io
    def save(self, *a, **k):
        raise NotImplementedError("use paddle.save / fleet utils checkpoint")

    def barrier_worker(self):
        pass


class TensorParallel(Layer):
    """ref meta_parallel.TensorParallel: the mp wrapper. Forward runs the
    layer unchanged — under GSPMD the TP layers' sharding hints place the
    weights, and inside shard_map regions fleet enters the 'mp' scope."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


fleet = _Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
distributed_scaler = fleet.distributed_scaler
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
