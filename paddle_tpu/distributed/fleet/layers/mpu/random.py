"""Per-parallel-axis RNG streams.

Reference parity: fleet/layers/mpu/random.py (U) — `RNGStatesTracker` with
'global_seed' (identical across mp ranks) and 'local_seed' (distinct per mp
rank) streams used for dropout in tensor-parallel blocks (SURVEY.md §2.2 P12).

TPU-native design: streams are fold_in-counter key streams (core.random). The
*local* stream folds `lax.axis_index('mp')` into every key when the mp axis is
live inside shard_map, giving each rank a distinct-but-deterministic stream
with zero cross-device state; eagerly (single controller, GSPMD) the model is
globally consistent anyway so local==global.
"""

from __future__ import annotations

import contextlib

import jax
from jax import lax

from .....core import random as _random
from .... import collective_ctx

MODEL_PARALLEL_RNG = "model_parallel_rng"

_tracker = _random.default_tracker


def get_rng_state_tracker():
    return _tracker()


class _LocalKeyStream(_random._KeyStream):
    """Key stream that decorrelates per-mp-rank when 'mp' is mapped."""

    def next_key(self):
        k = super().next_key()
        if collective_ctx.current_axis("mp") is not None:
            k = jax.random.fold_in(k, lax.axis_index("mp"))
        return k


def model_parallel_random_seed(seed=None):
    """ref `model_parallel_random_seed`: seed the tracker with a dedicated
    model-parallel stream."""
    tr = _tracker()
    base = int(seed) if seed is not None else 0
    tr.states[MODEL_PARALLEL_RNG] = _LocalKeyStream(base + 1024)
    return tr


@contextlib.contextmanager
def model_parallel_rng():
    """Dropout inside TP blocks draws from the per-rank stream."""
    tr = _tracker()
    if MODEL_PARALLEL_RNG not in tr.states:
        model_parallel_random_seed(0)
    with tr.rng_state(MODEL_PARALLEL_RNG):
        yield
