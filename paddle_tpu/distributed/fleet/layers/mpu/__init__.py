from . import mp_ops, random
from .random import get_rng_state_tracker, model_parallel_random_seed
