"""Model-parallel communication primitives.

Reference parity: fleet/layers/mpu/mp_ops.py (U) — `_c_identity`, `_c_split`,
`_c_concat`, `_mp_allreduce`, `_c_lookup_table`,
`_c_softmax_with_cross_entropy` over the mp NCCL ring (SURVEY.md §2.2 P12,
§2.1 N14).

TPU-native design: each primitive is a named-axis op executed inside
`shard_map` over the 'mp' mesh axis. The asymmetric-gradient pairs
(identity-forward/allreduce-backward and its dual) are `jax.custom_vjp`
functions; the rest (all_gather / psum_scatter) use the vjps jax derives.
Outside any mapped axis these all degrade to the mp=1 identity, matching the
reference's single-rank behavior.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .....core.op_call import apply
from .....core.tensor import Tensor
from .... import collective_ctx
from ....shard_map_compat import axis_size as _axis_size


# ---------------------------------------------------------------- raw (jnp)

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def identity_fwd_allreduce_bwd(x, axis_name):
    """ref `_c_identity`: forward passes through; backward all-reduces the
    gradient over the mp axis (the column-parallel input path)."""
    return x


def _id_fwd(x, axis_name):
    return x, None


def _id_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


identity_fwd_allreduce_bwd.defvjp(_id_fwd, _id_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def allreduce_fwd_identity_bwd(x, axis_name):
    """ref `_mp_allreduce` (and row-parallel output path): forward
    all-reduces partial sums; backward passes the gradient through."""
    return lax.psum(x, axis_name)


def _ar_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _ar_bwd(axis_name, _, g):
    return (g,)


allreduce_fwd_identity_bwd.defvjp(_ar_fwd, _ar_bwd)


def split_last_dim(x, axis_name):
    """ref `_c_split`: keep this rank's slice of the last dim. Backward is the
    all-gather jax derives from dynamic_slice + the surrounding shard_map."""
    n = _axis_size(axis_name)
    i = lax.axis_index(axis_name)
    size = x.shape[-1] // n
    return lax.dynamic_slice_in_dim(x, i * size, size, axis=-1)


def concat_last_dim(x, axis_name):
    """ref `_c_concat`: all-gather shards and concatenate on the last dim."""
    return lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def gather_axis(x, axis_name, axis):
    """all-gather along `axis` (sequence-parallel gather)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter_axis(x, axis_name, axis):
    """reduce-scatter along `axis` (sequence-parallel reduce path)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def vocab_parallel_embedding_lookup(ids, local_weight, axis_name):
    """ref `_c_lookup_table` + VocabParallelEmbedding.forward: each rank owns
    rows [i*per, (i+1)*per) of the embedding table; out-of-range ids produce
    zeros and the partial lookups are summed over the mp axis."""
    n = _axis_size(axis_name)
    i = lax.axis_index(axis_name)
    per = local_weight.shape[0]
    start = i * per
    local_ids = ids - start
    mask = (local_ids >= 0) & (local_ids < per)
    safe = jnp.where(mask, local_ids, 0)
    out = jnp.take(local_weight, safe, axis=0)
    out = out * mask[..., None].astype(out.dtype)
    return lax.psum(out, axis_name)


def _vp_ce_compute(local_logits, labels, axis_name, ignore_index):
    i = lax.axis_index(axis_name)
    per = local_logits.shape[-1]
    start = i * per

    f32 = local_logits.astype(jnp.float32)
    lmax = lax.pmax(lax.stop_gradient(jnp.max(f32, axis=-1)), axis_name)
    shifted = f32 - lmax[..., None]
    sumexp = lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis_name)

    local_label = labels - start
    in_range = (local_label >= 0) & (local_label < per)
    safe = jnp.where(in_range, local_label, 0)
    tgt = jnp.take_along_axis(shifted, safe[..., None], axis=-1)[..., 0]
    tgt = lax.psum(tgt * in_range.astype(tgt.dtype), axis_name)

    loss = jnp.log(sumexp) - tgt
    keep = None
    if ignore_index >= 0:
        keep = (labels != ignore_index).astype(loss.dtype)
        loss = loss * keep
    return loss, (shifted, sumexp, safe, in_range, keep)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(local_logits, labels, axis_name,
                                 ignore_index=-100):
    """ref `_c_softmax_with_cross_entropy` (a fused CUDA op with a
    hand-written grad): softmax cross-entropy over vocab-sharded logits
    without materializing the full vocab dim — global max via pmax, global
    sum-exp via psum, target logit recovered by masking.

    The VJP is hand-written like the reference's: grad wrt the local logits is
    (softmax_local − onehot_local)·ḡ with NO backward collective. Relying on
    jax's psum-transpose(=psum) here would scale grads by the axis size,
    because the replicated loss double-counts each rank's contribution."""
    return _vp_ce_compute(local_logits, labels, axis_name, ignore_index)[0]


def _vp_ce_fwd(local_logits, labels, axis_name, ignore_index):
    loss, res = _vp_ce_compute(local_logits, labels, axis_name, ignore_index)
    proto = jnp.zeros((0,), local_logits.dtype)  # carries the input dtype
    return loss, (res, labels.shape, proto)


def _vp_ce_bwd(axis_name, ignore_index, saved, g):
    (shifted, sumexp, safe, in_range, keep), lbl_shape, proto = saved
    in_dtype = proto.dtype
    p = jnp.exp(shifted) / sumexp[..., None]
    onehot = (jax.nn.one_hot(safe, shifted.shape[-1], dtype=p.dtype)
              * in_range[..., None].astype(p.dtype))
    gg = g if keep is None else g * keep
    grad = gg[..., None] * (p - onehot)
    import numpy as np
    zero_lbl = np.zeros(lbl_shape, dtype=jax.dtypes.float0)
    return grad.astype(in_dtype), zero_lbl


vocab_parallel_cross_entropy.defvjp(_vp_ce_fwd, _vp_ce_bwd)


# ------------------------------------------------------------- Tensor-level

def _axis_or_none(group=None):
    """Resolve the live mp axis: the group's mesh axis if it is currently
    mapped (inside shard_map), else None (mp=1 degenerate)."""
    name = getattr(group, "axis_name", None) or "mp"
    return collective_ctx.current_axis(name)


def _c_identity(t, group=None, skip_c_identity_dynamic=False):
    axis = _axis_or_none(group)
    if axis is None:
        return t
    return apply(lambda x: identity_fwd_allreduce_bwd(x, axis), t)


def mp_allreduce_sum(t, group=None):
    axis = _axis_or_none(group)
    if axis is None:
        return t
    return apply(lambda x: allreduce_fwd_identity_bwd(x, axis), t)


_mp_allreduce = mp_allreduce_sum


def _c_split(t, group=None):
    axis = _axis_or_none(group)
    if axis is None:
        return t
    return apply(lambda x: split_last_dim(x, axis), t)


def _c_concat(t, group=None):
    axis = _axis_or_none(group)
    if axis is None:
        return t
    return apply(lambda x: concat_last_dim(x, axis), t)


def _parallel_linear(x, weight, bias, gather_out=True, group=None):
    """ref `_parallel_linear` helper: column-parallel matmul."""
    axis = _axis_or_none(group)
    y = apply(
        lambda a, w: jnp.matmul(a, w),
        _c_identity(x, group) if axis else x,
        weight,
    )
    if bias is not None:
        y = apply(lambda a, b: a + b, y, bias)
    if axis and gather_out:
        y = _c_concat(y, group)
    return y
