"""Tensor-parallel layers.

Reference parity: fleet/meta_parallel/parallel_layers/mp_layers.py (U) —
`VocabParallelEmbedding`, `ColumnParallelLinear`, `RowParallelLinear`,
`ParallelCrossEntropy` over the mp comm group (SURVEY.md §2.2 P12).

TPU-native design — one layer, two regimes:

* **GSPMD (eager / pjit)**: the layer holds the FULL logical weight tagged
  with a `_sharding_axes` hint (e.g. `(None, 'mp')`). Math is the plain
  dense op; when the params are device_put/constrained to the hybrid mesh,
  XLA's SPMD partitioner emits exactly the Megatron collectives the
  reference hand-codes (identity/allreduce pairs).
* **Explicit shard_map**: when the 'mp' axis is live (collective_ctx), the
  layer computes on its LOCAL shard with the explicit named-axis primitives
  in mpu.mp_ops — identical math to the reference's comm-ring version, used
  by the pipeline runtime and by parity tests.

Weights are always *initialized* full-size so serial and sharded runs see
bit-identical parameters (slice k of the full init == rank k's shard).
"""

from __future__ import annotations

import jax.numpy as jnp

from .....core.op_call import apply
from .....nn import functional as F
from .....nn.initializer import Normal, XavierNormal
from .....nn.layer.layers import Layer
from ....topology import get_hybrid_communicate_group
from .... import collective_ctx
from ...layers.mpu import mp_ops


def _mp_world(mp_group):
    if mp_group is not None:
        return mp_group.nranks
    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg is not None else 1


def _shard_mode(world):
    return world > 1 and collective_ctx.current_axis("mp") is not None


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._world = _mp_world(mp_group)
        self._group = mp_group
        if num_embeddings % self._world:
            raise ValueError(
                f"vocab size {num_embeddings} not divisible by mp degree {self._world}")
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0) if weight_attr is None else None,
        )
        self.weight.is_distributed = self._world > 1
        self.weight._sharding_axes = ("mp", None)

    def forward(self, x):
        if _shard_mode(self._world):
            return apply(
                lambda ids, w: mp_ops.vocab_parallel_embedding_lookup(ids, w, "mp"),
                x, self.weight)
        return F.embedding(x, self.weight)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}, mp={self._world}"


class ColumnParallelLinear(Layer):
    """Linear with the OUT features sharded over 'mp' (Y = X·[W1|W2|...])."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self._world = _mp_world(mp_group)
        self._group = mp_group
        self.gather_output = gather_output
        if out_features % self._world:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree {self._world}")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        self.weight.is_distributed = self._world > 1
        self.weight._sharding_axes = (None, "mp")
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.is_distributed = self._world > 1
            self.bias._sharding_axes = ("mp",)
        else:
            self.bias = None

    def forward(self, x):
        if _shard_mode(self._world):
            x = mp_ops._c_identity(x, self._group)
            y = apply(lambda a, w: jnp.matmul(a, w), x, self.weight)
            if self.bias is not None:
                y = apply(lambda a, b: a + b, y, self.bias)
            if self.gather_output:
                y = mp_ops._c_concat(y, self._group)
            return y
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"mp={self._world}, gather_output={self.gather_output}")


class RowParallelLinear(Layer):
    """Linear with the IN features sharded over 'mp'; partial products are
    summed over the axis, bias added after the reduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self._world = _mp_world(mp_group)
        self._group = mp_group
        self.input_is_parallel = input_is_parallel
        if in_features % self._world:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree {self._world}")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal(),
        )
        self.weight.is_distributed = self._world > 1
        self.weight._sharding_axes = ("mp", None)
        if has_bias:
            # bias is NOT sharded: applied once, after the cross-rank reduce
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias._sharding_axes = (None,)
        else:
            self.bias = None

    def forward(self, x):
        if _shard_mode(self._world):
            if not self.input_is_parallel:
                x = mp_ops._c_split(x, self._group)
            y = apply(lambda a, w: jnp.matmul(a, w), x, self.weight)
            y = mp_ops.mp_allreduce_sum(y, self._group)
            if self.bias is not None:
                y = apply(lambda a, b: a + b, y, self.bias)
            return y
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in={self._in_features}, out={self._out_features}, "
                f"mp={self._world}, input_is_parallel={self.input_is_parallel}")


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over vocab-sharded logits (ref
    `ParallelCrossEntropy` / `c_softmax_with_cross_entropy`)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self._group = mp_group
        self._world = _mp_world(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if _shard_mode(self._world):
            return apply(
                lambda lg, lb: mp_ops.vocab_parallel_cross_entropy(
                    lg, lb, "mp", ignore_index=self.ignore_index)[..., None],
                input, label)
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
