"""Pipeline-parallel layer description & segmentation.

Reference parity: fleet/meta_parallel/parallel_layers/pp_layers.py (U) —
`LayerDesc`, `SharedLayerDesc`, `PipelineLayer` with uniform / 'layer:Class'
segmentation (SURVEY.md §2.2 P13).

TPU-native design: single-controller SPMD materializes EVERY stage's layers in
one process (the reference materializes only the local rank's stage); the
per-stage partition feeds the compiled ppermute schedule in
pipeline_parallel.py, and weight tying (SharedLayerDesc) is plain object reuse
instead of a broadcast group.

Interface contract for the compiled schedule: stages 0..S-2 must emit the
same-shaped hidden activation (stage 0 maps the raw input microbatch to it);
the final stage's layers + loss_fn map hidden → scalar loss.
"""

from __future__ import annotations

import math

from .....nn.layer.layers import Layer


class LayerDesc:
    """Lazy layer constructor (ref LayerDesc)."""

    def __init__(self, layer_class, *inputs, **kwargs):
        self.layer_class = layer_class
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_class, Layer):
            raise TypeError(f"LayerDesc expects an nn.Layer subclass, got {layer_class}")

    def build_layer(self):
        return self.layer_class(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_class.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer (ref SharedLayerDesc: embedding/output tying across
    stages). Single-controller: every position with the same `key` reuses ONE
    instance, so tying is structural, with `forward_func` selecting the view."""

    def __init__(self, key, layer_class, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_class, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class _FuncWrapper(Layer):
    """Plain callables in the desc list (paddle allows lambdas)."""

    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _SharedView(Layer):
    """A reuse of a SharedLayerDesc instance at another pipeline position."""

    def __init__(self, inner, forward_func=None):
        super().__init__()
        self._inner_ref = [inner]  # hide from sublayer registry: params are
        # owned (and counted) by the first occurrence
        self._forward_func = forward_func

    def forward(self, *args, **kwargs):
        inner = self._inner_ref[0]
        if self._forward_func is not None:
            return self._forward_func(inner, *args, **kwargs)
        return inner(*args, **kwargs)


class PipelineLayer(Layer):
    """ref PipelineLayer: takes the desc list, segments it into pp stages.

    `forward` runs the full serial model (the pp=1 path and the parity
    reference); the compiled 1F1B/GPipe schedule lives in PipelineParallel.
    """

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        from ....topology import get_hybrid_communicate_group

        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._num_virtual_stages = num_virtual_pipeline_stages or 1
        self._topo = topology
        if num_stages is None:
            hcg = get_hybrid_communicate_group()
            num_stages = (hcg.get_pipe_parallel_world_size()
                          if hcg is not None else 1)
        self._num_stages = int(num_stages)

        # materialize descs; SharedLayerDesc instances dedupe by key
        shared = {}
        self._shared_owner_prefix = {}  # id(inner) -> registered name prefix
        items = []
        for d in layers:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in shared:
                    items.append(_SharedView(shared[d.layer_name], d.forward_func))
                else:
                    inner = d.build_layer()
                    shared[d.layer_name] = inner
                    items.append(inner if d.forward_func is None
                                 else _SharedView(inner, d.forward_func))
                    if d.forward_func is not None:
                        # first occurrence must still own the params
                        items[-1].add_sublayer("shared", inner)
                        self._shared_owner_prefix[id(inner)] = \
                            f"{len(items) - 1}.shared"
                    else:
                        self._shared_owner_prefix[id(inner)] = \
                            str(len(items) - 1)
            elif isinstance(d, LayerDesc):
                items.append(d.build_layer())
            elif isinstance(d, Layer):
                items.append(d)
            elif callable(d):
                items.append(_FuncWrapper(d))
            else:
                raise TypeError(f"unsupported pipeline item {d!r}")
        self.run_function = items
        for i, it in enumerate(items):
            self.add_sublayer(str(i), it)

        self._seg_method = seg_method
        self.segment_parts = self._segment(seg_method)

    # ------------------------------------------------------------ segmenting
    def _segment(self, method):
        # interleaved/VPP: segment into S·V chunks; chunk d executes on rank
        # d % S (Megatron virtual-pipeline layout, ref pp_layers.py
        # get_stage_from_index)
        n, S = len(self.run_function), self._num_stages * self._num_virtual_stages
        if S == 1:
            return [0, n]
        if method.startswith("layer:"):
            cls_name = method.split(":", 1)[1]
            block_idx = [i for i, it in enumerate(self.run_function)
                         if type(it).__name__ == cls_name]
            if not block_idx:
                raise ValueError(f"seg_method {method!r}: no layer of class "
                                 f"{cls_name} in the desc list")
            if len(block_idx) < S:
                raise ValueError(f"{len(block_idx)} {cls_name} blocks cannot "
                                 f"fill {S} stages")
            per = len(block_idx) / S
            bounds = [0]
            for k in range(1, S):
                bounds.append(block_idx[math.ceil(k * per)])
            bounds.append(n)
            return bounds
        # uniform: equal item count per stage
        if n < S:
            raise ValueError(f"{n} layers cannot fill {S} stages")
        per = n / S
        return [0] + [math.ceil(k * per) for k in range(1, S)] + [n]

    # ------------------------------------------------------------ access
    @property
    def num_stages(self):
        return self._num_stages

    @property
    def num_virtual_stages(self):
        return self._num_virtual_stages

    def get_stage_layers(self, chunk_id):
        """Layers of chunk `chunk_id` (== stage id when V == 1; with VPP,
        chunk d runs on rank d % num_stages)."""
        lo, hi = self.segment_parts[chunk_id], self.segment_parts[chunk_id + 1]
        return self.run_function[lo:hi]

    def stage_param_names(self, stage_id):
        """All param names owned by rank `stage_id` (its V chunks)."""
        names = []
        for chunk in range(stage_id, self._num_stages * self._num_virtual_stages,
                           self._num_stages):
            lo, hi = self.segment_parts[chunk], self.segment_parts[chunk + 1]
            for i in range(lo, hi):
                prefix = str(i)
                for n, _ in self._sub_layers[prefix].named_parameters(prefix=prefix):
                    names.append(n)
        return names

    def chunk_param_names(self, chunk_id):
        """Param names READ by chunk `chunk_id`: its own items' params plus
        the owner-registered params of any _SharedView (tied weights used
        here but owned by the first occurrence's chunk). The 1F1B schedule
        differentiates each chunk w.r.t. exactly this set, so tied-weight
        gradients from every using chunk are computed and summed (ref
        shared-weight allreduce, fleet pipeline_parallel.py (U))."""
        lo, hi = self.segment_parts[chunk_id], self.segment_parts[chunk_id + 1]
        names = []
        for i in range(lo, hi):
            it = self.run_function[i]
            if isinstance(it, _SharedView) and not it._sub_layers:
                inner = it._inner_ref[0]
                prefix = self._shared_owner_prefix[id(inner)]
                names.extend(n for n, _ in
                             inner.named_parameters(prefix=prefix))
            else:
                names.extend(n for n, _ in self._sub_layers[str(i)]
                             .named_parameters(prefix=str(i)))
        return names

    # ------------------------------------------------------------ serial ref
    def forward(self, x):
        for it in self.run_function:
            x = it(x)
        return x

    def compute_loss(self, logits, labels):
        if self._loss_fn is None:
            raise RuntimeError("PipelineLayer built without loss_fn")
        return self._loss_fn(logits, labels)
