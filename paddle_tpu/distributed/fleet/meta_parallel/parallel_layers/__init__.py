from .mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ...layers.mpu.random import get_rng_state_tracker, model_parallel_random_seed
