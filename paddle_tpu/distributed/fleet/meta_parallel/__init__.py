"""fleet.meta_parallel (ref: fleet/meta_parallel/__init__.py (U))."""
from .parallel_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
from .parallel_layers.pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .pipeline_parallel import PipelineParallel
