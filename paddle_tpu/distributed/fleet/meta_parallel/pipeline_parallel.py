"""Pipeline-parallel runtime: the microbatch schedule as ONE compiled program.

Reference parity: fleet/meta_parallel/pipeline_parallel.py +
pp_utils/p2p_communication.py (U) — `PipelineParallel.train_batch` running
1F1B/GPipe microbatch schedules with NCCL p2p between stage ranks
(SURVEY.md §2.2 P13, §3.3 step 4).

TPU-native design: no p2p runtime, no shape negotiation, no interceptor
actors. The whole schedule is data: a `lax.scan` over ticks inside
`shard_map` over the 'pp' mesh axis; at each tick every device runs its
stage (one `lax.switch` branch — embedding stage consumes the raw
microbatch, the final stage computes the loss) and hands its activation to
the next stage with a ring `lax.ppermute`. XLA overlaps the permute with
compute (the reference needs dedicated comm streams + event sync for this,
SURVEY.md §2.1 N13). Backward is `jax.grad` through the scan, with
`jax.checkpoint` per stage giving the recompute variant (ref
recompute_interval). Warmup/drain bubbles are masked ticks, matching GPipe.

Memory semantics (measured via compiled memory_analysis, see
tests/test_pipeline_parallel.py::TestPipelineMemory): the default schedule
is GPipe-shaped — `jax.grad` through the scan retains per-tick residuals,
so activation memory grows O(accumulate_steps). With recompute_interval>0
the per-tick residual is only the tick's BOUNDARY tensors (microbatch input
+ ppermuted hidden + labels; measured ≈1× boundary size per microbatch, ~5×
smaller than the no-remat variant), so the growth constant is small: for
transformer stages whose internal activations are 30–60× the boundary
hidden, remat-GPipe uses LESS activation memory than true 1F1B's
O(depth × full-activations) whenever accumulate_steps < ~30× depth, at the
usual one-extra-forward cost.

For the no-remat / long-schedule regime the reference's literal 1F1B
schedule (pp_utils/p2p_communication.py (U)) is available as an opt-in:
`strategy={"pipeline_configs": {"schedule": "1f1b"}}` hand-interleaves
per-microbatch forward and backward on a deterministic clock with vjp
residuals in per-slot depth-bounded ring buffers, bounding in-flight FULL
activations by pipeline depth with no extra forward (see
_pipeline_pure_fn_1f1b; measured in TestPipeline1F1B — per-extra-microbatch
growth < 0.2× GPipe's at accumulate_steps=32). It composes with
SharedLayerDesc weight tying (every using chunk differentiates the tied
weight; contributions psum across 'pp') and with
num_virtual_pipeline_stages>1 (Megatron interleaved chunk layout).

Gradient flow across stages needs no reducer: stage params enter replicated
(in_spec P()), so shard_map's transpose inserts the psum that sums each
param's gradient from its owning stage (zeros elsewhere) — and the same psum
doubles as the dp gradient all-reduce when the 'dp' axis is live.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ....core import random as random_state
from ....core import tape as _tape
from ....core.op_call import apply
from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from ... import collective_ctx
from ...topology import get_hybrid_communicate_group
from .parallel_layers.pp_layers import PipelineLayer

from ...shard_map_compat import NO_CHECK as _SM_NO_CHECK, shard_map


@jax.custom_vjp
def _grad_scale(x, s):
    return x


def _grad_scale_fwd(x, s):
    return x, s


def _grad_scale_bwd(s, g):
    return (g * s, None)


_grad_scale.defvjp(_grad_scale_fwd, _grad_scale_bwd)


class PipelineParallel(Layer):
    """ref PipelineParallel (meta_parallel): wraps a PipelineLayer and runs
    the compiled microbatch schedule. Composition with dp is native (batch
    sharded over 'dp'); with mp, stage layers built from mpu mp-layers run
    in explicit shard mode — their params enter shard_map pre-sharded over
    the 'mp' axis and the layers issue the Megatron collectives inline."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg or get_hybrid_communicate_group()
        cfg = {}
        if strategy is not None:
            cfg = getattr(strategy, "pipeline_configs", None) or (
                strategy if isinstance(strategy, dict) else {})
            # Accept the documented nested form {"pipeline_configs": {...}}
            # for plain-dict strategies too (ref DistributedStrategy shape).
            if isinstance(cfg, dict) and isinstance(
                    cfg.get("pipeline_configs"), dict):
                cfg = cfg["pipeline_configs"]
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = cfg.get("micro_batch_size", None)
        self.schedule = cfg.get("schedule", "gpipe")
        self._train_step = None
        self._pp_fn_cache = {}

    # ----------------------------------------------------------- plumbing
    def forward(self, x):
        return self._layers(x)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def no_sync(self):
        import contextlib

        return contextlib.nullcontext()

    # ----------------------------------------------------------- schedule
    def _schedule_env(self):
        """Setup shared by every schedule builder: mesh axis liveness,
        per-param shard_map specs (pp×mp composition: mp-layer params with
        is_distributed enter pre-sharded over 'mp' via their hint, the rest
        replicated), and the mp cotangent-rescale wrapper.

        On the rescale: the replicated scalar loss (out_specs P()) seeds
        each shard with cotangent 1/N_mesh; the psum-over-pp transpose
        restores the pp factor and the replicated-param transpose psums over
        'mp' (identical grads on every mp rank), so replicated params come
        out exact — but mp-SHARDED params have no mp psum and land at 1/mp
        of the true grad, so their cotangent gets scaled back by mp."""
        pp = self._layers
        mesh = self._hcg.mesh
        names = list(pp.state_dict())
        dp_live = "dp" in mesh.shape and mesh.shape["dp"] > 1
        mp_live = "mp" in mesh.shape and mesh.shape["mp"] > 1
        live_axes = ("pp", "mp") if mp_live else ("pp",)
        sd0 = pp.state_dict()

        def _param_spec(t):
            axes = getattr(t, "_sharding_axes", None)
            if mp_live and getattr(t, "is_distributed", False) and axes:
                return P(*axes)
            return P()

        param_specs = tuple(_param_spec(sd0[n]) for n in names)

        def rescale_mp(params):
            if not mp_live:
                return params
            mp_size = float(mesh.shape["mp"])
            return tuple(_grad_scale(p, mp_size) if spec != P() else p
                         for p, spec in zip(params, param_specs))

        batch_spec = P(None, "dp") if dp_live else P()
        return (mesh, names, dp_live, mp_live, live_axes, param_specs,
                rescale_mp, batch_spec)

    @staticmethod
    def _run_items(items, t_in):
        for it in items:
            t_in = it(t_in)
        return t_in

    def _pipeline_pure_fn(self, n_micro):
        """Build pure(x_mbs, y_mbs, key, *params) -> scalar loss, shard_mapped
        over the hybrid mesh with the tick loop inside."""
        if (getattr(self, "schedule", "gpipe") == "1f1b"
                and self._layers.num_stages > 1):
            return self._pipeline_pure_fn_1f1b(n_micro)
        if n_micro in self._pp_fn_cache:
            return self._pp_fn_cache[n_micro]

        pp = self._layers
        S = pp.num_stages
        V = getattr(pp, "num_virtual_stages", 1)
        if V > 1:
            return self._pipeline_pure_fn_interleaved(n_micro)
        remat = pp._recompute_interval and pp._recompute_interval > 0
        (mesh, names, dp_live, mp_live, live_axes, param_specs,
         rescale_mp, batch_spec) = self._schedule_env()
        run_items = self._run_items

        def spmd(x_mbs, y_mbs, base_key, *params):
            s = lax.axis_index("pp")
            params = rescale_mp(params)

            with _tape.no_grad(), collective_ctx.axis_scope(*live_axes), \
                    pp.use_state(dict(zip(names, params))):

                def make_branch(k):
                    items = pp.get_stage_layers(k)
                    is_last = k == S - 1

                    def br(x_mb, hid, y_mb, key):
                        with random_state.fork_rng(key):
                            if S == 1:
                                out = run_items(items, Tensor(x_mb))
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return hid, jnp.mean(loss._data).astype(jnp.float32)
                            if is_last:
                                out = run_items(items, Tensor(hid))
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return hid, jnp.mean(loss._data).astype(jnp.float32)
                            src = Tensor(x_mb) if k == 0 else Tensor(hid)
                            out = run_items(items, src)
                            return (out._data.astype(hid.dtype),
                                    jnp.zeros((), jnp.float32))

                    return jax.checkpoint(br) if remat else br

                branches = [make_branch(k) for k in range(S)]

                # hidden buffer: shape/dtype of stage 0's output
                def stage0_shape(x_mb, key):
                    with random_state.fork_rng(key):
                        out = run_items(pp.get_stage_layers(0), Tensor(x_mb))
                    return out._data

                probe_key = jax.random.fold_in(base_key, 0)
                if S > 1:
                    hid_sd = jax.eval_shape(stage0_shape, x_mbs[0], probe_key)
                else:
                    hid_sd = jax.eval_shape(lambda a: a[..., :1].astype(jnp.float32),
                                            x_mbs[0])
                hid0 = jnp.zeros(hid_sd.shape, hid_sd.dtype)

                T = n_micro + S - 1
                perm = [(i, (i + 1) % S) for i in range(S)]

                def tick(carry, t):
                    hid, loss_sum = carry
                    key_t = jax.random.fold_in(base_key, t)
                    m0 = jnp.clip(t, 0, n_micro - 1)
                    mL = jnp.clip(t - (S - 1), 0, n_micro - 1)
                    x_mb = jnp.take(x_mbs, m0, axis=0)
                    y_mb = jnp.take(y_mbs, mL, axis=0)
                    hid_next, loss_t = lax.switch(
                        jnp.minimum(s, S - 1), branches, x_mb, hid, y_mb, key_t)
                    valid = (t >= S - 1) & (t - (S - 1) < n_micro)
                    loss_sum = loss_sum + jnp.where(valid, loss_t, 0.0)
                    if S > 1:
                        hid_next = lax.ppermute(hid_next, "pp", perm)
                    return (hid_next, loss_sum), None

                (_, loss_sum), _ = lax.scan(
                    tick, (hid0, jnp.zeros((), jnp.float32)), jnp.arange(T))

            loss = lax.psum(loss_sum, "pp") / n_micro if S > 1 else loss_sum / n_micro
            if dp_live:
                loss = lax.pmean(loss, "dp")
            return loss

        def pure(x_mbs, y_mbs, base_key, *params):
            f = shard_map(
                spmd, mesh=mesh,
                in_specs=(batch_spec, batch_spec, P()) + param_specs,
                out_specs=P(), **_SM_NO_CHECK)
            return f(x_mbs, y_mbs, base_key, *params)

        self._pp_fn_cache[n_micro] = (pure, names)
        return self._pp_fn_cache[n_micro]

    def _pipeline_pure_fn_interleaved(self, n_micro):
        """Interleaved / VPP schedule (ref Megatron-style interleaved 1F1B,
        fleet pipeline_parallel.py with num_virtual_pipeline_stages): the
        model is cut into S·V chunks, rank r owns chunks {r, r+S, ...}; per
        tick every rank runs its V chunks (slot j carries sweep j's
        activation) and the ring ppermutes all V slots at once, with rank 0
        shifting slot j-1's arrival into slot j (sweep boundary)."""
        key = ("vpp", n_micro)
        if key in self._pp_fn_cache:
            return self._pp_fn_cache[key]

        pp = self._layers
        S = pp.num_stages
        V = pp.num_virtual_stages
        D = S * V
        if S == 1:
            raise ValueError("num_virtual_pipeline_stages>1 requires pp>1")
        remat = pp._recompute_interval and pp._recompute_interval > 0
        (mesh, names, dp_live, mp_live, live_axes, param_specs,
         rescale_mp, batch_spec) = self._schedule_env()
        run_items = self._run_items

        def spmd(x_mbs, y_mbs, base_key, *params):
            s = lax.axis_index("pp")
            params = rescale_mp(params)

            with _tape.no_grad(), collective_ctx.axis_scope(*live_axes), \
                    pp.use_state(dict(zip(names, params))):

                def make_chunk_branch(d):
                    items = pp.get_stage_layers(d)
                    is_last = d == D - 1

                    def br(x_mb, hid, y_mb, key):
                        with random_state.fork_rng(key):
                            src = Tensor(x_mb) if d == 0 else Tensor(hid)
                            if is_last:
                                out = run_items(items, src)
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return hid, jnp.mean(loss._data).astype(jnp.float32)
                            out = run_items(items, src)
                            return (out._data.astype(hid.dtype),
                                    jnp.zeros((), jnp.float32))

                    return jax.checkpoint(br) if remat else br

                # slot j on rank r runs chunk j*S + r
                branches = [[make_chunk_branch(j * S + r) for r in range(S)]
                            for j in range(V)]

                def stage0_shape(x_mb, key):
                    with random_state.fork_rng(key):
                        out = run_items(pp.get_stage_layers(0), Tensor(x_mb))
                    return out._data

                probe_key = jax.random.fold_in(base_key, 0)
                hid_sd = jax.eval_shape(stage0_shape, x_mbs[0], probe_key)
                hid0 = jnp.zeros((V,) + hid_sd.shape, hid_sd.dtype)

                T = n_micro + D - 1
                perm = [(i, (i + 1) % S) for i in range(S)]

                def tick(carry, t):
                    hid, loss_sum = carry          # hid [V, ...hidden]
                    key_t = jax.random.fold_in(base_key, t)
                    m0 = jnp.clip(t, 0, n_micro - 1)
                    mL = jnp.clip(t - (D - 1), 0, n_micro - 1)
                    x_mb = jnp.take(x_mbs, m0, axis=0)
                    y_mb = jnp.take(y_mbs, mL, axis=0)
                    outs = []
                    loss_t = jnp.zeros((), jnp.float32)
                    for j in range(V):
                        h_j, l_j = lax.switch(jnp.minimum(s, S - 1),
                                              branches[j], x_mb, hid[j],
                                              y_mb, jax.random.fold_in(key_t, j))
                        outs.append(h_j)
                        loss_t = loss_t + l_j
                    hid_out = jnp.stack(outs)          # [V, ...]
                    valid = (t >= D - 1) & (t - (D - 1) < n_micro)
                    loss_sum = loss_sum + jnp.where(valid, loss_t, 0.0)
                    permuted = lax.ppermute(hid_out, "pp", perm)
                    # sweep boundary: at rank 0, slot j's next input is what
                    # rank S-1's slot j-1 just sent (slot 0 consumes x_mb)
                    shifted = jnp.concatenate(
                        [jnp.zeros_like(permuted[:1]), permuted[:-1]], axis=0)
                    hid_next = jnp.where(s == 0, shifted, permuted)
                    return (hid_next, loss_sum), None

                (_, loss_sum), _ = lax.scan(
                    tick, (hid0, jnp.zeros((), jnp.float32)), jnp.arange(T))

            loss = lax.psum(loss_sum, "pp") / n_micro
            if dp_live:
                loss = lax.pmean(loss, "dp")
            return loss

        def pure(x_mbs, y_mbs, base_key, *params):
            f = shard_map(
                spmd, mesh=mesh,
                in_specs=(batch_spec, batch_spec, P()) + param_specs,
                out_specs=P(), **_SM_NO_CHECK)
            return f(x_mbs, y_mbs, base_key, *params)

        self._pp_fn_cache[key] = (pure, names)
        return self._pp_fn_cache[key]

    # ------------------------------------------------------------- 1F1B
    def _pipeline_pure_fn_1f1b(self, n_micro):
        """Literal 1F1B schedule (ref pp_utils/p2p_communication.py (U),
        SURVEY §2.2 P13): per-microbatch forward and backward are
        hand-interleaved on a deterministic clock — with D = S·V chunks
        (V = num_virtual_pipeline_stages, Megatron interleaved layout:
        chunk d runs on rank d % S as virtual slot d // S), fwd of
        microbatch m runs for chunk d at tick m+d, its backward at tick
        m+2(D-1)-d — so in-flight FULL activations are bounded by
        O(depth·V) ring slots, not O(accumulate_steps) as in the jax.grad
        GPipe schedule. No recompute: each chunk's vjp residuals are
        byte-packed into per-slot fixed ring buffers and replayed at the
        backward tick; parameter gradients accumulate in f32 on each
        USING chunk and psum across 'pp' at the end — so SharedLayerDesc
        weight tying works: every chunk that reads a tied weight (owner or
        _SharedView) differentiates it and the contributions sum, matching
        the reference's shared-weight allreduce semantics
        (fleet/meta_parallel/pipeline_parallel.py (U)). The result is
        exposed through jax.custom_vjp so TrainStep's ordinary jax.grad
        path consumes the hand-computed gradients."""
        key_c = ("1f1b", n_micro)
        if key_c in self._pp_fn_cache:
            return self._pp_fn_cache[key_c]

        import numpy as np

        pp = self._layers
        S = pp.num_stages
        assert S > 1  # S == 1 dispatches to the serial GPipe builder
        V = getattr(pp, "num_virtual_stages", 1)
        D = S * V
        (mesh, names, dp_live, mp_live, live_axes, param_specs,
         _rescale_mp, batch_spec) = self._schedule_env()
        run_items = self._run_items
        M = n_micro
        # per-slot residual ring: chunk d's residual lives from its fwd
        # tick m+d to its bwd tick m+2(D-1)-d, so slot j (chunks j·S+r)
        # needs at most 2(D-1-j·S)+1 concurrent microbatches (r=0 worst)
        K_slot = [max(1, 2 * (D - 1 - j * S) + 1) for j in range(V)]
        sd0 = pp.state_dict()
        trainable = {n for n in names if not sd0[n].stop_gradient}
        # param indices READ by each chunk (owned + tied-in via
        # _SharedView); only trainable ones get hand-computed grads
        chunk_idx = []
        for d in range(D):
            reads = set(pp.chunk_param_names(d))
            chunk_idx.append([i for i, n in enumerate(names)
                              if n in trainable and n in reads])
        users_of = {}
        for d, idxs in enumerate(chunk_idx):
            for i in idxs:
                users_of.setdefault(i, []).append(d)

        def spmd(x_mbs, y_mbs, base_key, *params):
            s = lax.axis_index("pp")

            with _tape.no_grad(), collective_ctx.axis_scope(*live_axes):

                # ---------- per-chunk primals over (hid?, sub_params)
                def chunk_prim(d):
                    items = pp.get_stage_layers(d)
                    idxs = chunk_idx[d]

                    def f(x_in, sub, y_mb, key):
                        arrays = dict(zip(names, params))
                        for j, i in enumerate(idxs):
                            arrays[names[i]] = sub[j]
                        with random_state.fork_rng(key), \
                                pp.use_state(arrays):
                            out = run_items(items, Tensor(x_in))
                            if d == D - 1:
                                loss = pp.compute_loss(out, Tensor(y_mb))
                                return jnp.mean(loss._data).astype(jnp.float32)
                            return out._data
                    return f

                prims = [chunk_prim(d) for d in range(D)]

                # hidden boundary shape from chunk 0 (same for all chunk
                # boundaries, as in the GPipe schedule)
                probe_key = jax.random.fold_in(base_key, 0)
                sub0 = tuple(params[i] for i in chunk_idx[0])
                hid_sd = jax.eval_shape(
                    lambda x, sb, ky: prims[0](x, sb, y_mbs[0], ky),
                    x_mbs[0], sub0, probe_key)
                hid_shape, hid_dtype = hid_sd.shape, hid_sd.dtype

                # ---------- vjp plumbing per chunk
                def vjp_raw(k, x_in, sub, y_mb, key):
                    """(out, pullback) over the diff args (hid for k>0,
                    sub params)."""
                    if k == 0:
                        prim = lambda sb: prims[0](x_in, sb, y_mb, key)
                        return jax.vjp(prim, sub)
                    prim = lambda xi, sb: prims[k](xi, sb, y_mb, key)
                    return jax.vjp(prim, x_in, sub)

                def vjp_parts(k, x_in, sub, y_mb, key):
                    """(out, treedef, leaves, mask). jax.vjp's pullback is
                    a tree_util.Partial pytree whose leaves are the
                    residual arrays in jaxpr-determined (deterministic)
                    order — a far stronger cross-trace contract than
                    closure_convert's retrace-hoisting, whose const order
                    drifts on mp graphs. mask[j] >= 0 marks leaves that
                    are ambient values (stage params, or the stage-0
                    microbatch input) — tick-invariant or re-indexable,
                    NOT buffered (buffering them would copy the stage's
                    full parameters into every ring slot); mask[j] == -2
                    marks non-array leaves (static, taken from the
                    rebuild trace)."""
                    y, pb = vjp_raw(k, x_in, sub, y_mb, key)
                    leaves, treedef = jax.tree.flatten(pb)
                    ambient = list(sub) + ([x_in] if k == 0 else [])
                    mask = []
                    for c in leaves:
                        if not hasattr(c, "dtype"):
                            mask.append(-2)
                            continue
                        hit = -1
                        for ai, a in enumerate(ambient):
                            if c is a:
                                hit = ai
                                break
                        mask.append(hit)
                    return y, treedef, leaves, mask

                # static residual layouts from a probe trace (a real
                # trace, not eval_shape — the ambient mask needs tracer
                # identity); the probe's dead compute is DCE'd by XLA
                # probe inputs must be TRACERS (zeros constants would
                # make input-derived residuals trace-constants there but
                # hoisted consts in the real branches — layout drift)
                def tracer_hid():
                    seed = jnp.ravel(x_mbs)[0].astype(jnp.float32) * 0.0
                    return jnp.broadcast_to(seed.astype(hid_dtype),
                                            hid_shape)

                def probe(k):
                    # closure_convert hoists outer tracers only from a
                    # NESTED trace; what gets hoisted also depends on HOW
                    # far up the tracer lives. Mirror the real schedule's
                    # nesting exactly — vjp inside a cond whose parent
                    # trace carries (x_mb, y_mb), like the switch branch
                    # does — so the probe's residual layout matches the
                    # real branches'. The trace-time mask assertions in
                    # the branches are the safety net.
                    sub = tuple(params[i] for i in chunk_idx[k])
                    box = {}

                    def outer(ops):
                        x_op, y_op = ops

                        def inner(hid_op):
                            xi = x_op if k == 0 else hid_op
                            _, _, leaves, mask = vjp_parts(
                                k, xi, sub, y_op, probe_key)
                            box["specs"] = [
                                jax.ShapeDtypeStruct(c.shape, c.dtype)
                                for j, c in enumerate(leaves)
                                if mask[j] == -1]
                            box["mask"] = mask
                            return jnp.zeros((), jnp.float32)

                        return lax.cond(jnp.bool_(True), inner, inner,
                                        tracer_hid())

                    lax.cond(jnp.bool_(True), outer, outer,
                             (x_mbs[0], y_mbs[0]))
                    return box["specs"], box["mask"]

                probes = [probe(k) for k in range(D)]
                res_specs = [p[0] for p in probes]
                res_masks = [p[1] for p in probes]

                def nbytes(sdt):
                    it = 1 if sdt.dtype == jnp.bool_ else jnp.dtype(sdt.dtype).itemsize
                    return int(np.prod(sdt.shape)) * it

                R = max(1, max(sum(nbytes(c) for c in res_specs[k])
                               for k in range(D)))
                # grad-accumulator layout from the shard_map-LOCAL param
                # shapes (mp-sharded params are smaller in here than the
                # host-global sd0 view)
                sizes = [sum(int(np.prod(params[i].shape))
                             for i in chunk_idx[k]) for k in range(D)]
                G = max(1, max(sizes))

                def pack_bytes(consts, total):
                    parts = []
                    for c in consts:
                        if c.dtype == jnp.bool_:
                            c = c.astype(jnp.uint8)
                        b = jax.lax.bitcast_convert_type(c, jnp.uint8)
                        parts.append(b.reshape(-1))
                    flat = (jnp.concatenate(parts) if parts
                            else jnp.zeros((0,), jnp.uint8))
                    return jnp.pad(flat, (0, total - flat.shape[0]))

                def unpack_bytes(flat, specs):
                    out, off = [], 0
                    for sdt in specs:
                        shape = tuple(sdt.shape)
                        if sdt.dtype == jnp.bool_:
                            n = int(np.prod(shape))
                            out.append(flat[off:off + n].reshape(shape)
                                       .astype(jnp.bool_))
                            off += n
                            continue
                        isz = jnp.dtype(sdt.dtype).itemsize
                        n = int(np.prod(shape)) * isz
                        b = flat[off:off + n]
                        b = (b.reshape(shape + (isz,)) if isz > 1
                             else b.reshape(shape))
                        out.append(jax.lax.bitcast_convert_type(b, sdt.dtype))
                        off += n
                    return out

                def pack_grads(dsub, k):
                    parts = [d.astype(jnp.float32).reshape(-1) for d in dsub]
                    flat = (jnp.concatenate(parts) if parts
                            else jnp.zeros((0,), jnp.float32))
                    return jnp.pad(flat, (0, G - flat.shape[0]))

                zeros_hid = jnp.zeros(hid_shape, hid_dtype)

                # ---------- one tick of the schedule, per-RANK branch
                # (rank r runs its V chunks {r, r+S, ...} every tick)
                def rank_branch(r):
                    def fwd_for(d, sub, x_mb, y_mb, key_d):
                        def run(x_in_hid):
                            xi = x_mb if d == 0 else x_in_hid
                            if d == D - 1:
                                # loss chunk: backward runs in the same
                                # tick, straight through the raw pullback
                                y, pb = vjp_raw(d, xi, sub, y_mb, key_d)
                                cts = pb(jnp.float32(1.0 / M))
                                dx, dsub = cts
                                return (zeros_hid,
                                        dx.astype(hid_dtype),
                                        jnp.zeros((R,), jnp.uint8),
                                        pack_grads(dsub, d), y)
                            y, _, leaves, mask = vjp_parts(
                                d, xi, sub, y_mb, key_d)
                            if mask != res_masks[d]:
                                raise AssertionError(
                                    f"1f1b chunk {d}: residual layout "
                                    f"drifted between traces: probe="
                                    f"{res_masks[d]} fwd={mask}")
                            specs = [jax.ShapeDtypeStruct(c.shape, c.dtype)
                                     for jj, c in enumerate(leaves)
                                     if mask[jj] == -1]
                            if specs != res_specs[d]:
                                raise AssertionError(
                                    f"1f1b chunk {d}: residual SPECS "
                                    f"drifted between traces: probe="
                                    f"{res_specs[d]} fwd={specs}")
                            var = [c for jj, c in enumerate(leaves)
                                   if mask[jj] == -1]
                            return (y.astype(hid_dtype), zeros_hid,
                                    pack_bytes(var, R),
                                    jnp.zeros((G,), jnp.float32),
                                    jnp.zeros((), jnp.float32))
                        return run

                    def br(x_mb, y_mb, hid, ct, res_bufs, t):
                        outs, ct_outs, accs = [], [], []
                        new_bufs = list(res_bufs)
                        loss_t = jnp.zeros((), jnp.float32)
                        for j in range(V):
                            d = j * S + r
                            Kj = K_slot[j]
                            sub = tuple(params[i] for i in chunk_idx[d])
                            key_d = jax.random.fold_in(
                                jax.random.fold_in(base_key, t), d)
                            fwd_valid = (t >= d) & (t - d < M)
                            mf = jnp.clip(t - d, 0, M - 1)

                            def fwd_skip(hid_in):
                                return (zeros_hid, zeros_hid,
                                        jnp.zeros((R,), jnp.uint8),
                                        jnp.zeros((G,), jnp.float32),
                                        jnp.zeros((), jnp.float32))

                            y_out, ct_fused, res_new, acc1, loss_m = \
                                lax.cond(fwd_valid,
                                         fwd_for(d, sub, x_mb, y_mb, key_d),
                                         fwd_skip, hid[j])
                            buf = new_bufs[j]
                            buf = lax.dynamic_update_index_in_dim(
                                buf,
                                jnp.where(fwd_valid, res_new,
                                          lax.dynamic_index_in_dim(
                                              buf, mf % Kj, keepdims=False)),
                                mf % Kj, axis=0)
                            new_bufs[j] = buf

                            if d == D - 1:
                                outs.append(y_out)
                                ct_outs.append(ct_fused)
                                accs.append(acc1)
                                loss_t = loss_t + loss_m
                                continue

                            mb = t - (2 * (D - 1) - d)
                            bwd_valid = (mb >= 0) & (mb < M)
                            mbc = jnp.clip(mb, 0, M - 1)

                            def bwd_go(ct_in, d=d, sub=sub, buf=buf,
                                       mbc=mbc, key_d=key_d):
                                slot = lax.dynamic_index_in_dim(
                                    buf, mbc % K_slot[d // S],
                                    keepdims=False)
                                var = unpack_bytes(slot, res_specs[d])
                                # rebuild the pullback structure from a
                                # dummy trace (same jaxpr => same Partial
                                # treedef; the dummy's leaf VALUES are
                                # replaced, so its forward compute is
                                # DCE'd; the dummy hid must be a tracer —
                                # see probe)
                                x_bwd = (jnp.take(x_mbs, mbc, axis=0)
                                         if d == 0 else ct_in * 0)
                                _, treedef, leaves_d, mask = vjp_parts(
                                    d, x_bwd, sub, y_mb, key_d)
                                if mask != res_masks[d]:
                                    raise AssertionError(
                                        f"1f1b chunk {d}: residual layout "
                                        f"drifted between traces: probe="
                                        f"{res_masks[d]} bwd={mask}")
                                ambient = list(sub) + (
                                    [x_bwd] if d == 0 else [])
                                leaves, vi = [], 0
                                for jj in range(len(mask)):
                                    if mask[jj] >= 0:
                                        leaves.append(ambient[mask[jj]])
                                    elif mask[jj] == -2:
                                        leaves.append(leaves_d[jj])
                                    else:
                                        leaves.append(var[vi].astype(
                                            leaves_d[jj].dtype))
                                        vi += 1
                                pb2 = jax.tree.unflatten(treedef, leaves)
                                cts = pb2(ct_in.astype(hid_dtype))
                                if d == 0:
                                    return zeros_hid, pack_grads(cts[0], d)
                                dx, dsub = cts
                                return (dx.astype(hid_dtype),
                                        pack_grads(dsub, d))

                            def bwd_skip(ct_in):
                                return zeros_hid, jnp.zeros((G,),
                                                            jnp.float32)

                            dx_out, acc2 = lax.cond(bwd_valid, bwd_go,
                                                    bwd_skip, ct[j])
                            outs.append(y_out)
                            ct_outs.append(dx_out)
                            accs.append(acc1 + acc2)
                        return (jnp.stack(outs), jnp.stack(ct_outs),
                                tuple(new_bufs), jnp.stack(accs), loss_t)

                    return br

                branches = [rank_branch(r) for r in range(S)]
                perm_fwd = [(i, (i + 1) % S) for i in range(S)]
                perm_bwd = [(i, (i - 1) % S) for i in range(S)]
                T = M + 2 * (D - 1)

                def tick(carry, t):
                    hid, ct, res_bufs, acc, loss_sum = carry
                    m0 = jnp.clip(t, 0, M - 1)
                    mL = jnp.clip(t - (D - 1), 0, M - 1)
                    x_mb = jnp.take(x_mbs, m0, axis=0)
                    y_mb = jnp.take(y_mbs, mL, axis=0)
                    y_out, ct_out, res_bufs, dacc, loss_m = lax.switch(
                        jnp.minimum(s, S - 1), branches,
                        x_mb, y_mb, hid, ct, res_bufs, t)
                    hid_p = lax.ppermute(y_out, "pp", perm_fwd)
                    ct_p = lax.ppermute(ct_out, "pp", perm_bwd)
                    if V > 1:
                        # sweep boundaries (Megatron layout): rank 0's
                        # slot j is fed by rank S-1's slot j-1 (slot 0
                        # consumes the raw microbatch); rank S-1's ct
                        # slot j is fed by rank 0's slot j+1 (the loss
                        # chunk, slot V-1, seeds its own cotangent)
                        hid_shift = jnp.concatenate(
                            [jnp.zeros_like(hid_p[:1]), hid_p[:-1]], axis=0)
                        hid_next = jnp.where(s == 0, hid_shift, hid_p)
                        ct_shift = jnp.concatenate(
                            [ct_p[1:], jnp.zeros_like(ct_p[:1])], axis=0)
                        ct_next = jnp.where(s == S - 1, ct_shift, ct_p)
                    else:
                        hid_next, ct_next = hid_p, ct_p
                    return (hid_next, ct_next, res_bufs, acc + dacc,
                            loss_sum + loss_m), None

                carry0 = (jnp.zeros((V,) + hid_shape, hid_dtype),
                          jnp.zeros((V,) + hid_shape, hid_dtype),
                          tuple(jnp.zeros((K_slot[j], R), jnp.uint8)
                                for j in range(V)),
                          jnp.zeros((V, G), jnp.float32),
                          jnp.zeros((), jnp.float32))
                (_, _, _, acc, loss_sum), _ = lax.scan(
                    tick, carry0, jnp.arange(T))

            loss = lax.psum(loss_sum, "pp") / M
            if dp_live:
                loss = lax.pmean(loss, "dp")

            # unpack per-param grads from every USING chunk's accumulator
            # (offsets over the LOCAL shard shapes, matching pack_grads);
            # tied params sum their contributions across chunks — the
            # reference's shared-weight grad sync
            offsets = [dict() for _ in range(D)]
            for d in range(D):
                off = 0
                for i in chunk_idx[d]:
                    offsets[d][i] = off
                    off += int(np.prod(params[i].shape))
            grads = []
            for i, n in enumerate(names):
                p = params[i]
                users = users_of.get(i)
                if not users:
                    grads.append(jnp.zeros_like(p))
                    continue
                size = int(np.prod(p.shape))
                g_i = jnp.zeros(p.shape, jnp.float32)
                for d in users:
                    jslot, r = divmod(d, S)
                    gsl = lax.dynamic_slice(
                        acc[jslot], (offsets[d][i],), (size,))
                    g_i = g_i + gsl.reshape(p.shape) * \
                        (s == r).astype(jnp.float32)
                # psum over pp sums the using chunks' grads (zeros on
                # non-user ranks); over mp nothing is needed — the mp
                # ops' custom vjps (identity/allreduce pairs) already
                # make replicated-param grads identical on every mp rank,
                # and sharded-param grads are complete per shard
                g_i = lax.psum(g_i, "pp")
                if dp_live:
                    g_i = lax.pmean(g_i, "dp")
                grads.append(g_i.astype(p.dtype))
            return loss, tuple(grads)

        def run(x_mbs, y_mbs, base_key, *params):
            f = shard_map(
                spmd, mesh=mesh,
                in_specs=(batch_spec, batch_spec, P()) + param_specs,
                out_specs=(P(), param_specs), **_SM_NO_CHECK)
            return f(x_mbs, y_mbs, base_key, *params)

        from jax.dtypes import float0

        def _ct_zero(a):
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
                return jnp.zeros_like(a)
            return np.zeros(jnp.shape(a), float0)

        @jax.custom_vjp
        def pure(x_mbs, y_mbs, base_key, *params):
            return run(x_mbs, y_mbs, base_key, *params)[0]

        def pure_fwd(x_mbs, y_mbs, base_key, *params):
            loss, grads = run(x_mbs, y_mbs, base_key, *params)
            return loss, (grads, x_mbs, y_mbs, base_key)

        def pure_bwd(res, g):
            grads, x_mbs, y_mbs, base_key = res
            return (_ct_zero(x_mbs), _ct_zero(y_mbs), _ct_zero(base_key)) + \
                tuple((g * gr.astype(jnp.float32)).astype(gr.dtype)
                      for gr in grads)

        pure.defvjp(pure_fwd, pure_bwd)

        self._pp_fn_cache[key_c] = (pure, names)
        return self._pp_fn_cache[key_c]

    def _loss_fn_for(self, n_micro):
        pure, names = self._pipeline_pure_fn(n_micro)

        def loss_fn(model, x_mbs, y_mbs):
            sd = model.state_dict()
            key = random_state.next_key()
            return apply(pure, x_mbs, y_mbs, key,
                         *[sd[n] for n in names], _op_name="pipeline")

        return loss_fn

    def _split_micro(self, t):
        arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
        n = self.accumulate_steps
        if arr.shape[0] % n:
            raise ValueError(
                f"batch dim {arr.shape[0]} not divisible by accumulate_steps {n}")
        return Tensor(arr.reshape((n, arr.shape[0] // n) + arr.shape[1:]))

    def _place_state_on_mesh(self, optimizer):
        """Pre-place every model/optimizer state array onto the hybrid
        mesh — mp-distributed params to their `_sharding_axes` spec, the
        rest replicated — BEFORE the first compiled step. Host-created
        single-device params would otherwise enter step 1 with shardings
        that cannot alias the step's mesh-wide outputs: XLA silently
        copies every donated state buffer (a model-sized transient HBM
        spike at scale) and the sharding flip forces a second compile at
        step 2 (VERDICT r4: dryrun donation warnings)."""
        from jax.sharding import NamedSharding

        mesh = self._hcg.mesh
        mp_live = "mp" in mesh.shape and mesh.shape["mp"] > 1

        def target(t):
            axes = getattr(t, "_sharding_axes", None)
            if mp_live and getattr(t, "is_distributed", False) and axes:
                return NamedSharding(mesh, P(*axes))
            return NamedSharding(mesh, P())

        for t in self._layers.state_dict().values():
            sh = getattr(t._data, "sharding", None)
            want = target(t)
            if sh != want:
                t._data = jax.device_put(t._data, want)
            if optimizer is not None:
                # materialize the accumulator NOW (get-or-create) so its
                # fresh leaves — including 0-d beta-pow scalars created
                # without reference to the param — get placed as well: a
                # single stray SingleDeviceSharding input flips the
                # step-2 jit signature and forces the recompile this
                # pre-placement exists to prevent
                st = (optimizer._state_for(t)
                      if not t.stop_gradient else None)
                if st is not None:
                    repl = NamedSharding(mesh, P())

                    def place(a, _want=want, _repl=repl):
                        if not isinstance(a, jax.Array):
                            return a
                        # low-rank leaves (beta-pow scalars) can't take
                        # the param's spec — replicate them
                        w = (_want if a.ndim >= len(_want.spec)
                             else _repl)
                        return (jax.device_put(a, w)
                                if a.sharding != w else a)

                    optimizer._accumulators[id(t)] = jax.tree.map(
                        place, st)

    # ----------------------------------------------------------- API
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """ref train_batch: one full fwd+bwd+step over accumulate_steps
        microbatches, compiled once."""
        x, y = data
        x_mbs, y_mbs = self._split_micro(x), self._split_micro(y)
        if self._train_step is None:
            from ....jit.train_step import TrainStep

            self._place_state_on_mesh(optimizer)
            self._train_step = TrainStep(
                self._layers, self._loss_fn_for(self.accumulate_steps),
                optimizer, scaler=scaler)
            self._publish_schedule_skew()
        loss = self._train_step(x_mbs, y_mbs)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def _publish_schedule_skew(self):
        """Publish the pipeline-bubble skew gauge once per compiled
        schedule (the observability comms ledger; best-effort — a
        metrics failure must never fail training)."""
        try:
            from ....observability import comms as _obs_comms

            _obs_comms.publish_pipeline_schedule(
                self.schedule, self._layers.num_stages,
                self.accumulate_steps,
                virtual=getattr(self._layers, "num_virtual_stages", 1))
        except Exception:            # pragma: no cover - defensive
            pass

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        was_training = self._layers.training
        self._layers.eval()
        try:
            with _tape.no_grad():
                out = self._layers(x if isinstance(x, Tensor) else Tensor(x))
                if compute_loss:
                    return self._layers.compute_loss(
                        out, y if isinstance(y, Tensor) else Tensor(y))
                return out
        finally:
            if was_training:
                self._layers.train()
